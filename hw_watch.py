"""Relay-recovery watcher: probe periodically, then run the shared
round-5 measurement queue (``hw_steps.MEASUREMENT_STEPS`` — one
definition with ``hw_measure.py``) exactly once, under the relay lock.

Measurements run with NO timeout and are never killed: a SIGTERM'd
client is what wedges the single-tenant relay in the first place
(BENCHMARKS.md relay incident log).

Usage: nohup python hw_watch.py >> hw_watch.log 2>&1 &
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from pathlib import Path

ROOT = Path(__file__).parent
OUT = ROOT / "HW_MEASURE.jsonl"
PROBE_EVERY_S = 900

from hw_steps import MEASUREMENT_STEPS  # noqa: E402 — shared with hw_measure.py

STEPS: list[tuple[str, list[str]]] = MEASUREMENT_STEPS


def record(entry: dict) -> None:
    with OUT.open("a") as f:
        f.write(json.dumps(entry) + "\n")


def main() -> int:
    sys.path.insert(0, str(ROOT))
    from hops_tpu.runtime.relaylock import RelayBusy, relay_lock

    def child_env() -> dict:
        # Rebuilt per use: after relay_lock is acquired it must carry
        # the pass-through token relay_lock exports into os.environ
        # (a pre-acquisition snapshot would make children collide with
        # our own lock). PYTHONPATH appended, never prepended:
        # /root/.axon_site must stay first or the TPU plugin fails to
        # register.
        env = dict(os.environ)
        env["PYTHONPATH"] = ":".join(
            p for p in (env.get("PYTHONPATH"), str(ROOT)) if p
        )
        return env

    while True:
        proc = subprocess.run(
            [sys.executable, "bench.py", "--probe"],
            cwd=ROOT, env=child_env(), capture_output=True, text=True,
        )
        if '"ok": true' in proc.stdout:
            print("[hw_watch] relay recovered — running queue", flush=True)
            break
        if '"busy": true' in proc.stdout:
            print(f"[hw_watch] relay locked by another client; sleeping {PROBE_EVERY_S}s",
                  flush=True)
        else:
            print(f"[hw_watch] relay still wedged; sleeping {PROBE_EVERY_S}s", flush=True)
        time.sleep(PROBE_EVERY_S)
    try:
        with relay_lock("hw_watch.py queue"):
            return _run_queue(child_env())
    except RelayBusy as e:
        print(f"[hw_watch] {e}", flush=True)
        return 2


def _run_queue(env: dict) -> int:
    for name, cmd in STEPS:
        t0 = time.time()
        print(f"[hw_watch] {name}", flush=True)
        proc = subprocess.run(  # no timeout, ever
            cmd, cwd=ROOT, env=env, capture_output=True, text=True
        )
        record({
            "step": name,
            "rc": proc.returncode,
            "wall_s": round(time.time() - t0, 1),
            "stdout": proc.stdout[-4000:],
            "stderr": proc.stderr[-2000:],
            "ts": time.strftime("%Y-%m-%d %H:%M:%S"),
        })
        print(f"[hw_watch] {name}: rc={proc.returncode}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
