"""Relay-recovery watcher: probe periodically, then run queued hardware
measurements exactly once.

The queue: the decode-horizon continuous-batching A/B, the speculative
engine A/B, and the post-fix int8 decode re-run (the rest of the
round-4 agenda was banked by ``hw_measure.py`` — `HW_MEASURE.jsonl`).
Measurements run with NO timeout and are never killed: a SIGTERM'd
client is what wedges the single-tenant relay in the first place
(BENCHMARKS.md relay incident log).

Usage: nohup python hw_watch.py >> hw_watch.log 2>&1 &
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from pathlib import Path

ROOT = Path(__file__).parent
OUT = ROOT / "HW_MEASURE.jsonl"
PROBE_EVERY_S = 900

# Round-5 queue (round-4 review item #1a): every currently-unlogged
# claim gains an HW_MEASURE.jsonl line. Small compiles first — the
# relay has wedged itself on big compiles, so the decode evidence must
# be banked before the LM/ResNet compiles get a chance to take it down.
STEPS: list[tuple[str, list[str]]] = [
    # int8 decode kernel: both round-4 logged attempts failed Mosaic
    # lowering; the fix (4155d33) has no logged artifact.
    ("decode_int8", [sys.executable, "examples/decode_bench.py",
                     "--kv-dtype", "int8"]),
    # The composite the cache-bytes story is sold on — never logged green.
    ("decode_all_knobs", [sys.executable, "examples/decode_bench.py",
                          "--kv-dtype", "int8", "--kv-heads", "2",
                          "--window", "256"]),
    # O(valid) DMA-clamp evidence at shapes where the effect clears the
    # ~1 ms dispatch floor (new defaults: d_head 128, cap 16k, fixed-
    # valid capacity control row).
    ("valid_sweep", [sys.executable, "examples/decode_bench.py",
                     "--valid-sweep"]),
    # Continuous-batching A/Bs: engine vs static, then the dispatch-
    # floor levers (decode horizon, speculative decoding).
    ("decode_continuous_h1", [sys.executable, "examples/decode_bench.py",
                              "--continuous", "--batch", "4", "--tokens", "32",
                              "--layers", "4"]),
    ("decode_continuous_h8", [sys.executable, "examples/decode_bench.py",
                              "--continuous", "--batch", "4", "--tokens", "32",
                              "--layers", "4", "--horizon", "8"]),
    ("decode_continuous_spec", [sys.executable, "examples/decode_bench.py",
                                "--continuous", "--batch", "4", "--tokens", "32",
                                "--layers", "4", "--spec-k", "4"]),
    # The composed corner the dispatch-floor analysis asks for: one
    # dispatch buys up to horizon * spec_k tokens.
    ("decode_continuous_spec_h4", [sys.executable, "examples/decode_bench.py",
                                   "--continuous", "--batch", "4", "--tokens",
                                   "32", "--layers", "4", "--spec-k", "4",
                                   "--horizon", "4"]),
    # Offline drain: one fused dispatch per budget-sorted wave — the
    # batch-inference configuration built to beat static batching on a
    # dispatch-latency-bound link.
    ("decode_continuous_offline", [sys.executable, "examples/decode_bench.py",
                                   "--continuous", "--offline", "--batch", "4",
                                   "--tokens", "32", "--layers", "4"]),
    # LM training headline (round-4 review item #4): tokens/s/chip + MFU.
    ("lm_bench", [sys.executable, "bench.py", "--lm", "--no-probe"]),
    # Fresh driver-style headline artifact (compile cache warm: ~70 s).
    ("resnet50_bench", [sys.executable, "bench.py", "--no-probe"]),
]


def record(entry: dict) -> None:
    with OUT.open("a") as f:
        f.write(json.dumps(entry) + "\n")


def main() -> int:
    sys.path.insert(0, str(ROOT))
    from hops_tpu.runtime.relaylock import RelayBusy, relay_lock

    def child_env() -> dict:
        # Rebuilt per use: after relay_lock is acquired it must carry
        # the pass-through token relay_lock exports into os.environ
        # (a pre-acquisition snapshot would make children collide with
        # our own lock). PYTHONPATH appended, never prepended:
        # /root/.axon_site must stay first or the TPU plugin fails to
        # register.
        env = dict(os.environ)
        env["PYTHONPATH"] = ":".join(
            p for p in (env.get("PYTHONPATH"), str(ROOT)) if p
        )
        return env

    while True:
        proc = subprocess.run(
            [sys.executable, "bench.py", "--probe"],
            cwd=ROOT, env=child_env(), capture_output=True, text=True,
        )
        if '"ok": true' in proc.stdout:
            print("[hw_watch] relay recovered — running queue", flush=True)
            break
        if '"busy": true' in proc.stdout:
            print(f"[hw_watch] relay locked by another client; sleeping {PROBE_EVERY_S}s",
                  flush=True)
        else:
            print(f"[hw_watch] relay still wedged; sleeping {PROBE_EVERY_S}s", flush=True)
        time.sleep(PROBE_EVERY_S)
    try:
        with relay_lock("hw_watch.py queue"):
            return _run_queue(child_env())
    except RelayBusy as e:
        print(f"[hw_watch] {e}", flush=True)
        return 2


def _run_queue(env: dict) -> int:
    for name, cmd in STEPS:
        t0 = time.time()
        print(f"[hw_watch] {name}", flush=True)
        proc = subprocess.run(  # no timeout, ever
            cmd, cwd=ROOT, env=env, capture_output=True, text=True
        )
        record({
            "step": name,
            "rc": proc.returncode,
            "wall_s": round(time.time() - t0, 1),
            "stdout": proc.stdout[-4000:],
            "stderr": proc.stderr[-2000:],
            "ts": time.strftime("%Y-%m-%d %H:%M:%S"),
        })
        print(f"[hw_watch] {name}: rc={proc.returncode}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
