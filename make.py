#!/usr/bin/env python3
"""Docs-site generator — the reference's ``make.py`` re-done for this repo.

The reference walks every notebook, nbconverts it to markdown and feeds
Hugo (make.py:14-27, 79-106; SURVEY.md §2.1 "Docs generator"). Source
format here is code, not notebooks, so the generator walks the package
with ``ast`` (no imports, no JAX startup), renders one markdown page per
module from its docstring + public API signatures, and one per example
script, into ``site/content/``. Any static-site tool (Hugo included)
can consume the output; ``site/content/_index.md`` is the landing page.

Usage: ``python3 make.py [--out site]``
"""

from __future__ import annotations

import argparse
import ast
from pathlib import Path

ROOT = Path(__file__).parent


def _signature(node: ast.FunctionDef | ast.AsyncFunctionDef) -> str:
    args = []
    a = node.args
    defaults = [None] * (len(a.args) - len(a.defaults)) + list(a.defaults)
    for arg, default in zip(a.args, defaults):
        s = arg.arg
        if default is not None:
            s += f"={ast.unparse(default)}"
        args.append(s)
    if a.vararg:
        args.append(f"*{a.vararg.arg}")
    for arg, default in zip(a.kwonlyargs, a.kw_defaults):
        s = arg.arg
        if default is not None:
            s += f"={ast.unparse(default)}"
        args.append(s)
    if a.kwarg:
        args.append(f"**{a.kwarg.arg}")
    return f"{node.name}({', '.join(args)})"


def _first_line(doc: str | None) -> str:
    return (doc or "").strip().split("\n")[0]


def render_module(path: Path) -> tuple[str, str] | None:
    """Returns ``(page_markdown, docstring_first_line)`` or None."""
    tree = ast.parse(path.read_text())
    moddoc = ast.get_docstring(tree)
    if moddoc is None and not any(
        isinstance(n, (ast.FunctionDef, ast.ClassDef)) for n in tree.body
    ):
        return None
    lines = [f"# `{path.relative_to(ROOT)}`", ""]
    if moddoc:
        lines += [moddoc, ""]
    api = [n for n in tree.body if isinstance(n, (ast.FunctionDef, ast.ClassDef))]
    public = [n for n in api if not n.name.startswith("_")]
    if public:
        lines += ["## Public API", ""]
    for node in public:
        if isinstance(node, ast.ClassDef):
            lines.append(f"### class `{node.name}`")
            doc = _first_line(ast.get_docstring(node))
            if doc:
                lines += ["", doc, ""]
            for item in node.body:
                if isinstance(item, ast.FunctionDef) and not item.name.startswith("_"):
                    lines.append(f"- `{_signature(item)}` — {_first_line(ast.get_docstring(item))}")
            lines.append("")
        else:
            lines.append(f"### `{_signature(node)}`")
            doc = _first_line(ast.get_docstring(node))
            if doc:
                lines += ["", doc]
            lines.append("")
    return "\n".join(lines), _first_line(moddoc)


def build(out_dir: Path) -> list[Path]:
    content = out_dir / "content"
    content.mkdir(parents=True, exist_ok=True)
    written = []
    sources = sorted((ROOT / "hops_tpu").rglob("*.py")) + sorted(
        (ROOT / "examples").glob("*.py")
    )
    # Hand-written guides pass through unchanged.
    for guide in sorted((ROOT / "docs").glob("*.md")) if (ROOT / "docs").exists() else []:
        dst = content / guide.name
        dst.write_text(guide.read_text())
        written.append(dst)
    index = [
        "# hops-tpu",
        "",
        "TPU-native ML platform framework: experiment launchers, async parallel",
        "search, model registry/serving, feature store, jobs/orchestration —",
        "JAX/XLA/Pallas on the compute path, SPMD over TPU meshes for scale.",
        "",
        "## Modules",
        "",
    ]
    for src in sources:
        rendered = render_module(src)
        if rendered is None:
            continue
        page, first = rendered
        rel = src.relative_to(ROOT)
        slug = str(rel.with_suffix("")).replace("/", ".")
        dst = content / f"{slug}.md"
        dst.write_text(page)
        written.append(dst)
        index.append(f"- [`{rel}`]({slug}.md) — {first}")
    (content / "_index.md").write_text("\n".join(index) + "\n")
    written.append(content / "_index.md")
    return written


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default="site")
    args = parser.parse_args()
    pages = build(ROOT / args.out)
    print(f"wrote {len(pages)} pages under {args.out}/content")
