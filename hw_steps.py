"""The round-5 hardware measurement queue — ONE definition, imported by
both `hw_measure.py` (run-now sweep) and `hw_watch.py` (run-on-recovery
watcher), so the two entry points can never drift apart and log
different configurations under the same HW_MEASURE.jsonl step names.

Ordering rule: small compiles FIRST — the relay has twice wedged
itself on big (ResNet/LM-sized) compiles, so the decode evidence must
be banked before the large compiles get a chance to take it down.
"""

from __future__ import annotations

import sys

_DB = "examples/decode_bench.py"

#: (step name, argv) — every currently-unlogged round-4 claim gains an
#: HW_MEASURE.jsonl line (round-4 review item #1a), plus the round-5
#: engine levers.
MEASUREMENT_STEPS: list[tuple[str, list[str]]] = [
    # int8 decode kernel: both round-4 logged attempts failed Mosaic
    # lowering; the fix (4155d33) has no logged artifact.
    ("decode_int8", [sys.executable, _DB, "--kv-dtype", "int8"]),
    # The composite the cache-bytes story is sold on — never logged green.
    ("decode_all_knobs", [sys.executable, _DB, "--kv-dtype", "int8",
                          "--kv-heads", "2", "--window", "256"]),
    # O(valid) DMA-clamp evidence at shapes where the effect clears the
    # ~1 ms dispatch floor (round-5 defaults: d_head 128, cap 16k,
    # fixed-valid capacity control row).
    ("valid_sweep", [sys.executable, _DB, "--valid-sweep"]),
    # Continuous-batching A/Bs: engine vs static, then the dispatch-
    # floor levers (decode horizon, speculation, their composition,
    # and the fused offline drain).
    ("decode_continuous_h1", [sys.executable, _DB, "--continuous",
                              "--batch", "4", "--tokens", "32",
                              "--layers", "4"]),
    ("decode_continuous_h8", [sys.executable, _DB, "--continuous",
                              "--batch", "4", "--tokens", "32",
                              "--layers", "4", "--horizon", "8"]),
    ("decode_continuous_spec", [sys.executable, _DB, "--continuous",
                                "--batch", "4", "--tokens", "32",
                                "--layers", "4", "--spec-k", "4"]),
    ("decode_continuous_spec_h4", [sys.executable, _DB, "--continuous",
                                   "--batch", "4", "--tokens", "32",
                                   "--layers", "4", "--spec-k", "4",
                                   "--horizon", "4"]),
    ("decode_continuous_offline", [sys.executable, _DB, "--continuous",
                                   "--offline", "--batch", "4",
                                   "--tokens", "32", "--layers", "4"]),
    # LM serving tier (PR 6): paged KV cache + chunked prefill vs the
    # dense engine at equal memory under Poisson load — tiny model,
    # small compiles, so it rides before the big ones.
    ("lm_serving_bench", [sys.executable, "bench.py", "--lm-serving",
                          "--no-probe"]),
    # LM training headline (round-4 review item #4): tokens/s/chip +
    # MFU% at ~180M params — a LARGE compile, so it sits after the
    # decode evidence is banked.
    ("lm_bench", [sys.executable, "bench.py", "--lm", "--no-probe"]),
    # Fresh driver-style headline artifact (compile cache warm: ~70 s).
    ("resnet50_bench", [sys.executable, "bench.py", "--no-probe"]),
]
