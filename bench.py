"""Benchmark harness — the TPU port of the reference's benchmark notebook.

Reference: notebooks/ml/Benchmarks/benchmark.ipynb — ResNet-50 on
synthetic 224x224x3 batches under MirroredStrategy, bs=8/GPU (SURVEY.md
§6). Here: ResNet-50 fwd+bwd+SGD on synthetic data, bf16 on the MXU,
per-chip batch sized for TPU (64 by default), data-parallel over all
visible chips.

Prints ONE JSON line:
  {"metric": "resnet50_samples_per_sec_per_chip", "value": N,
   "unit": "samples/s/chip", "vs_baseline": N}

The reference publishes no numbers (BASELINE.md), so the recorded
baseline is self-measured: the first TPU run's value is stored in
BASELINE_SELF.json and later rounds report improvement against it.
"""

from __future__ import annotations

import argparse
import json
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_FILE = Path(__file__).parent / "BASELINE_SELF.json"
HW_LOG = Path(__file__).parent / "HW_MEASURE.jsonl"


def emit_stale_or_fail(metric: str, reason: str) -> "None":
    """Round-artifact fallback: re-emit the last green logged result.

    Two consecutive round artifacts went red (rc=1) because the relay
    was wedged at round end even though a green driver-style
    measurement existed hours earlier in HW_MEASURE.jsonl. When the
    live run is impossible (relay wedged or locked by a sweep), emit
    that last green result flagged ``"stale": true`` with its artifact
    coordinates, so the artifact carries information instead of only
    rc=1. Exits 0 on success, 1 only if no green result exists at all.
    """
    step_for = {
        "resnet50_samples_per_sec_per_chip": ("resnet50_bench",),
        "lm_tokens_per_sec_per_chip": ("lm_bench",),
    }
    wanted = step_for.get(metric, (metric,))
    best = None
    if HW_LOG.exists():
        for line in HW_LOG.read_text().splitlines():
            try:
                entry = json.loads(line)
            except ValueError:
                continue
            if entry.get("step") in wanted and entry.get("rc") == 0:
                for out_line in entry.get("stdout", "").splitlines():
                    try:
                        parsed = json.loads(out_line)
                    except ValueError:
                        continue
                    if parsed.get("metric") == metric:
                        best = (parsed, entry)  # keep LAST green
    if best is None:
        _note(f"no green {metric} result logged; nothing to fall back to ({reason})")
        raise SystemExit(1)
    parsed, entry = best
    parsed.update(
        stale=True,
        stale_reason=reason,
        stale_artifact=f"HW_MEASURE.jsonl step={entry['step']} ts={entry['ts']}",
    )
    print(json.dumps(parsed))
    raise SystemExit(0)


def _note(msg: str) -> None:
    """Progress line on stderr (stdout carries only the driver's JSON line).

    The relay makes first-compile slow (can exceed 10 min); without
    these lines a slow run and a wedged run look identical from the
    outside, and the only way to tell used to be killing the client —
    which is exactly what wedges the relay."""
    import sys

    print(f"[bench +{time.perf_counter() - _T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


_T0 = time.perf_counter()


def _enable_compile_cache() -> None:
    """Persist compiled executables under .jax_cache/ next to this file.

    The driver re-runs bench.py at round end with identical shapes; a
    warm cache turns the multi-minute relay compile into a fast load,
    shrinking the window in which a timeout/kill could wedge the relay."""
    cache = Path(__file__).parent / ".jax_cache"
    cache.mkdir(exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", str(cache))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


def _sync(tree) -> float:
    """Force completion via a device-to-host transfer.

    ``jax.block_until_ready`` is unreliable on relayed backends (it can
    return before execution finishes); an actual value transfer cannot.
    """
    return float(jax.tree.leaves(tree)[0])


def run_bench(
    per_chip_batch: int = 128,  # measured sweet spot on v5e (96/192/256 all slower, BENCHMARKS.md)
    image_size: int = 224,
    steps: int = 32,
    warmup: int = 16,
    smoke: bool = False,
    scan_chunk: int = 16,
    multihost: bool = False,
    remat: bool = False,
) -> dict:
    """Time the ResNet-50 train step with a device-side training loop.

    ``lax.scan`` runs ``scan_chunk`` optimizer steps per dispatch — the
    idiomatic TPU training loop (host only dispatches and reads
    metrics). This matters doubly here: the axon relay adds ~6 ms of
    host→device overhead per dispatch (measured, BENCHMARKS.md
    roofline section), which a per-step Python loop pays 16× more often.
    Pass ``scan_chunk=1`` for the per-dispatch variant.
    """
    from hops_tpu.models import common
    from hops_tpu.models.resnet import ResNet18ish, ResNet50
    from hops_tpu.parallel.strategy import CollectiveAllReduceStrategy, Strategy

    if smoke:
        model = ResNet18ish(dtype=jnp.float32, remat=remat)
        per_chip_batch, image_size, steps, warmup, scan_chunk = 8, 32, 4, 2, 2
    else:
        model = ResNet50(num_classes=1000, remat=remat)

    scan_chunk = min(scan_chunk, steps)  # --steps 8 means 8 steps, not 16
    # --multihost: the whole-slice mesh (XLA AllReduce over ICI/DCN),
    # launched one process per host via ``python -m hops_tpu.launch``
    # (RUNBOOK_v5e64.md). Default: all chips of this host.
    strategy = CollectiveAllReduceStrategy() if multihost else Strategy()
    n_chips = strategy.num_replicas_in_sync
    global_batch = per_chip_batch * n_chips
    local_batch = per_chip_batch * (jax.local_device_count() if multihost else n_chips)
    _note(f"backend up: {n_chips} chip(s), platform={jax.devices()[0].platform}")

    # Init under ONE jit at a tiny batch: params and BN stats are
    # batch-independent, and an eager init dispatches every conv as its
    # own relay compile round-trip — ~100 chances for a transient
    # UNAVAILABLE to kill the run (observed: rc=1 after 27 min inside
    # model.init, HW_MEASURE.jsonl 2026-07-31). One small compiled
    # program leaves the train-step compile as the only big request.
    import functools

    init_fn = functools.partial(
        common.create_bn_train_state,
        model,
        input_shape=(8, image_size, image_size, 3),
    )
    state = strategy.replicate(jax.jit(init_fn)(jax.random.PRNGKey(0)))
    _note("params initialized")
    train_step = common.make_bn_train_step()

    def multi_step(state, batch):
        def body(st, _):
            st, metrics = train_step(st, batch)
            return st, metrics["loss"]

        state, losses = jax.lax.scan(body, state, None, length=scan_chunk)
        return state, losses[-1]

    step_fn = strategy.step(multi_step)

    # Each process contributes its own local shard of the global batch.
    rs = np.random.RandomState(jax.process_index())
    batch = strategy.distribute_batch(
        {
            "image": rs.randn(local_batch, image_size, image_size, 3).astype(np.float32),
            "label": rs.randint(0, 10, (local_batch,)),
        }
    )

    _note(f"compiling + warmup ({max(1, warmup // scan_chunk)} dispatches of {scan_chunk} steps)")
    # The first dispatch carries the big train-step compile. The relay
    # intermittently answers a long compile with a transient
    # UNAVAILABLE (HW_MEASURE.jsonl 2026-07-31); one retry — with the
    # state re-initialized, since step_fn donates it — salvages the
    # run instead of losing a 27-minute attempt.
    try:
        state, loss = step_fn(state, batch)
    except jax.errors.JaxRuntimeError as e:
        if "UNAVAILABLE" not in str(e):
            raise
        _note(f"transient UNAVAILABLE on first compile; retrying once: {str(e)[:200]}")
        time.sleep(30)
        state = strategy.replicate(jax.jit(init_fn)(jax.random.PRNGKey(0)))
        state, loss = step_fn(state, batch)
    for _ in range(max(1, warmup // scan_chunk) - 1):
        state, loss = step_fn(state, batch)
    _sync(loss)
    _note("warmup done, timing")

    n_dispatch = max(1, steps // scan_chunk)  # whole dispatches only, never overshoot
    t0 = time.perf_counter()
    for _ in range(n_dispatch):
        state, loss = step_fn(state, batch)
    _sync(loss)
    elapsed = time.perf_counter() - t0

    total_steps = n_dispatch * scan_chunk
    samples_per_sec = global_batch * total_steps / elapsed
    return {
        "samples_per_sec": samples_per_sec,
        "samples_per_sec_per_chip": samples_per_sec / n_chips,
        "step_time_ms": elapsed / total_steps * 1e3,
        "n_chips": n_chips,
        "global_batch": global_batch,
        "platform": jax.devices()[0].platform,
    }


def probe_tpu(timeout_s: int = 120) -> dict:
    """Cheaply answer "is the TPU reachable?" without risking a wedge.

    The relay is single-tenant and killed clients can wedge it
    (BENCHMARKS.md operational note), so the probe runs a tiny matmul
    in a SUBPROCESS: on timeout the parent stops waiting but lets the
    child run to completion/exit on its own (never killed mid-
    handshake). This is how a recovered relay is detected so the real
    bench can re-measure — the smoke path stays CPU-pinned and would
    never notice recovery on its own.
    """
    import subprocess
    import sys
    import tempfile

    out = Path(tempfile.mkdtemp()) / "probe.json"
    code = (
        "import json, time, sys\n"
        "t0 = time.time()\n"
        "try:\n"
        "    import jax, jax.numpy as jnp\n"
        "    x = jnp.ones((256, 256), jnp.bfloat16)\n"
        "    v = float((x @ x).sum())\n"
        "    r = {'ok': True, 'platform': jax.devices()[0].platform,\n"
        "         'elapsed_s': round(time.time() - t0, 1)}\n"
        "except Exception as e:\n"
        "    r = {'ok': False, 'error': repr(e)[:300],\n"
        "         'elapsed_s': round(time.time() - t0, 1)}\n"
        f"open({str(out)!r}, 'w').write(json.dumps(r))\n"
        "print(json.dumps(r))\n"
    )
    # The child must not inherit our stdout/stderr: a still-running
    # child would hold the caller's pipes open past our return.
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        # Deliberately NOT killed: detach and report unreachable.
        return {"ok": False, "error": f"probe still hung after {timeout_s}s "
                "(child left to exit on its own; relay likely wedged)"}
    if out.exists():
        return json.loads(out.read_text())
    return {"ok": False, "error": f"probe exited rc={proc.returncode} without a result"}


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--smoke", action="store_true", help="tiny CPU-safe run")
    parser.add_argument(
        "--probe", action="store_true",
        help="subprocess TPU health check (never wedges); prints one JSON line",
    )
    parser.add_argument("--batch", type=int, default=128, help="per-chip batch size")
    parser.add_argument("--steps", type=int, default=32)
    parser.add_argument(
        "--scan-chunk", type=int, default=16, help="train steps per dispatch (1 = python loop)"
    )
    parser.add_argument(
        "--multihost", action="store_true",
        help="whole-slice data parallelism; launch per host via hops_tpu.launch "
        "(see RUNBOOK_v5e64.md)",
    )
    parser.add_argument(
        "--no-probe", action="store_true",
        help="skip the pre-run relay health probe (saves ~20s when known-healthy)",
    )
    parser.add_argument(
        "--remat", action="store_true",
        help="per-block rematerialization: trade recompute FLOPs for "
        "activation HBM bytes (A/B lever on the bandwidth-bound step)",
    )
    parser.add_argument(
        "--lock-wait", type=float, default=900.0,
        help="seconds to wait for the relay lock before falling back to "
        "the last green logged result (stale-flagged)",
    )
    args = parser.parse_args()

    import os

    from hops_tpu.runtime.relaylock import ENV_TOKEN, RelayBusy, current_owner, relay_lock

    if args.probe:
        # A probe during someone else's compile is itself a collision
        # risk, so a held lock answers "busy" WITHOUT touching the
        # relay. Lock holders' own probes (hw_watch) pass through via
        # the inherited token.
        owner = None if os.environ.get(ENV_TOKEN) else current_owner()
        if owner is not None:
            print(json.dumps({"metric": "tpu_probe", "ok": False, "busy": True,
                              "owner": owner}))
            return
        print(json.dumps({"metric": "tpu_probe", **probe_tpu()}))
        return

    metric = "resnet50_samples_per_sec_per_chip"
    if args.smoke:
        # The smoke run is documented CPU-safe; pin it there so it
        # never touches (or waits on) the single-tenant TPU relay —
        # and it needs no relay lock for the same reason. Env alone is
        # not enough when a sitecustomize pre-imported jax — same
        # trick as tests/conftest.py.
        jax.config.update("jax_platforms", "cpu")
        result = run_bench(
            per_chip_batch=args.batch, steps=args.steps, smoke=True,
            scan_chunk=args.scan_chunk, remat=args.remat,
        )
    elif args.multihost:
        # Multihost runs are launched one-process-per-host by
        # hops_tpu.launch against a real slice (no shared relay);
        # serialization is the launcher's job, not this lock's.
        _enable_compile_cache()
        result = run_bench(
            per_chip_batch=args.batch, steps=args.steps,
            scan_chunk=args.scan_chunk, multihost=True, remat=args.remat,
        )
    else:
        try:
            # The driver's round-end run would rather wait out a
            # sweep-in-progress than go red; 900 s covers the longest
            # observed warm-cache queue step.
            with relay_lock(f"bench.py {metric}", wait_s=args.lock_wait):
                if not args.no_probe:
                    # Fail over instead of hanging the driver: a wedged
                    # relay makes every backend call block forever, and
                    # killing the hung bench is what wedges the relay
                    # further. A healthy relay answers in ~20 s; 240 s
                    # means it is down — emit the last green result.
                    _note("probing relay health before committing to the real run")
                    health = probe_tpu(timeout_s=240)
                    if not health.get("ok"):
                        _note(f"relay unreachable: {health.get('error')}")
                        emit_stale_or_fail(metric, f"relay unreachable: {health.get('error')}")
                    _note(f"relay healthy ({health.get('platform')}, {health.get('elapsed_s')}s)")
                _enable_compile_cache()
                result = run_bench(
                    per_chip_batch=args.batch, steps=args.steps,
                    scan_chunk=args.scan_chunk, remat=args.remat,
                )
        except RelayBusy as e:
            _note(str(e))
            emit_stale_or_fail(metric, f"relay lock busy: {e.owner}")
    value = result["samples_per_sec_per_chip"]
    if args.multihost and jax.process_index() != 0:
        return  # one JSON line total: the chief's

    # Baselines are recorded per platform: the first real run on a
    # platform becomes that platform's baseline; later runs report
    # against it.
    baseline = None
    if not args.smoke:
        recorded = json.loads(BASELINE_FILE.read_text()) if BASELINE_FILE.exists() else {}
        entry = recorded.get(result["platform"])
        if entry is not None:
            baseline = entry.get("samples_per_sec_per_chip")
        else:
            recorded[result["platform"]] = {
                "samples_per_sec_per_chip": value,
                "platform": result["platform"],
                "recorded": time.strftime("%Y-%m-%d"),
            }
            BASELINE_FILE.write_text(json.dumps(recorded, indent=2))
            baseline = value

    print(
        json.dumps(
            {
                "metric": "resnet50_samples_per_sec_per_chip",
                "value": round(value, 2),
                "unit": "samples/s/chip",
                "vs_baseline": round(value / baseline, 4) if baseline else 1.0,
            }
        )
    )


if __name__ == "__main__":
    main()
