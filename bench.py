"""Benchmark harness — the TPU port of the reference's benchmark notebook.

Reference: notebooks/ml/Benchmarks/benchmark.ipynb — ResNet-50 on
synthetic 224x224x3 batches under MirroredStrategy, bs=8/GPU (SURVEY.md
§6). Here: ResNet-50 fwd+bwd+SGD on synthetic data, bf16 on the MXU,
per-chip batch sized for TPU (64 by default), data-parallel over all
visible chips.

Prints ONE JSON line:
  {"metric": "resnet50_samples_per_sec_per_chip", "value": N,
   "unit": "samples/s/chip", "vs_baseline": N}

The reference publishes no numbers (BASELINE.md), so the recorded
baseline is self-measured: the first TPU run's value is stored in
BASELINE_SELF.json and later rounds report improvement against it.
"""

from __future__ import annotations

import argparse
import json
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_FILE = Path(__file__).parent / "BASELINE_SELF.json"
HW_LOG = Path(__file__).parent / "HW_MEASURE.jsonl"


def emit_stale_or_fail(metric: str, reason: str, kind: str = "relay_error") -> "None":
    """Round-artifact fallback: re-emit the last green logged result.

    Two consecutive round artifacts went red (rc=1) because the relay
    was wedged at round end even though a green driver-style
    measurement existed hours earlier in HW_MEASURE.jsonl. When the
    live run is impossible (relay wedged or locked by a sweep), emit
    that last green result flagged ``"stale": true`` with its artifact
    coordinates, so the artifact carries information instead of only
    rc=1. Exits 0 on success, 1 only if no green result exists at all.

    ``kind`` labels WHY the reading is stale — ``probe_timeout`` (the
    health probe hung; BENCH_r04/r05's failure mode), ``relay_error``
    (the probe answered with an error), or ``relay_busy`` (lock held by
    a sweep) — so consumers can tell a wedged relay from a contended
    one instead of multichip readings silently going stale.
    """
    step_for = {
        "resnet50_samples_per_sec_per_chip": ("resnet50_bench",),
        "lm_tokens_per_sec_per_chip": ("lm_bench",),
        "lm_serving_tokens_per_sec_per_chip": ("lm_serving_bench",),
    }
    wanted = step_for.get(metric, (metric,))
    best = None
    if HW_LOG.exists():
        for line in HW_LOG.read_text().splitlines():
            try:
                entry = json.loads(line)
            except ValueError:
                continue
            if entry.get("step") in wanted and entry.get("rc") == 0:
                for out_line in entry.get("stdout", "").splitlines():
                    try:
                        parsed = json.loads(out_line)
                    except ValueError:
                        continue
                    if parsed.get("metric") == metric and not parsed.get("stale"):
                        # A logged line already flagged stale is itself a
                        # fallback re-emission: chaining it would launder
                        # its provenance (stale_reason/artifact would be
                        # overwritten with this run's). Only genuinely
                        # green measurements are re-emittable.
                        best = (parsed, entry)  # keep LAST green
    if best is None:
        _note(f"no green {metric} result logged; nothing to fall back to ({reason})")
        raise SystemExit(1)
    parsed, entry = best
    if "vs_baseline" in parsed:
        # The ratio was computed against the baseline as of the ORIGINAL
        # measurement; re-emitting it under the live key lets a consumer
        # read an hours-old comparison as this round's number. Move it
        # aside rather than dropping it — the stale line stays
        # self-describing.
        parsed["vs_baseline_stale"] = parsed.pop("vs_baseline")
    parsed.update(
        stale=True,
        stale_reason=reason,
        stale_kind=kind,
        stale_artifact=f"HW_MEASURE.jsonl step={entry['step']} ts={entry['ts']}",
    )
    print(json.dumps(parsed))
    raise SystemExit(0)


def _note(msg: str) -> None:
    """Progress line on stderr (stdout carries only the driver's JSON line).

    The relay makes first-compile slow (can exceed 10 min); without
    these lines a slow run and a wedged run look identical from the
    outside, and the only way to tell used to be killing the client —
    which is exactly what wedges the relay."""
    import sys

    print(f"[bench +{time.perf_counter() - _T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


_T0 = time.perf_counter()


def _enable_compile_cache() -> None:
    """Persist compiled executables under .jax_cache/ next to this file.

    The driver re-runs bench.py at round end with identical shapes; a
    warm cache turns the multi-minute relay compile into a fast load,
    shrinking the window in which a timeout/kill could wedge the relay."""
    cache = Path(__file__).parent / ".jax_cache"
    cache.mkdir(exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", str(cache))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


def _sync(tree) -> float:
    """Force completion via a device-to-host transfer.

    ``jax.block_until_ready`` is unreliable on relayed backends (it can
    return before execution finishes); an actual value transfer cannot.
    """
    return float(jax.tree.leaves(tree)[0])


def _timed_loop(step_fn, state, batch, *, steps, warmup, scan_chunk, remake_state):
    """Shared timing harness for every bench: compile+warmup with the
    one-shot transient-UNAVAILABLE retry, then whole-dispatch timing.

    The retry exists because the relay intermittently answers a long
    compile with a transient UNAVAILABLE (HW_MEASURE.jsonl 2026-07-31);
    ``remake_state`` re-initializes because ``step_fn`` donates its
    state. One harness, not a per-bench copy, so relay-resilience fixes
    land everywhere at once. Returns ``(elapsed_s, total_steps)``.
    """
    _note(f"compiling + warmup ({max(1, warmup // scan_chunk)} dispatches of {scan_chunk} steps)")
    try:
        state, loss = step_fn(state, batch)
    except jax.errors.JaxRuntimeError as e:
        if "UNAVAILABLE" not in str(e):
            raise
        _note(f"transient UNAVAILABLE on first compile; retrying once: {str(e)[:200]}")
        time.sleep(30)
        state = remake_state()
        state, loss = step_fn(state, batch)
    for _ in range(max(1, warmup // scan_chunk) - 1):
        state, loss = step_fn(state, batch)
    _sync(loss)
    _note("warmup done, timing")

    n_dispatch = max(1, steps // scan_chunk)  # whole dispatches only, never overshoot
    t0 = time.perf_counter()
    for _ in range(n_dispatch):
        state, loss = step_fn(state, batch)
    _sync(loss)
    return time.perf_counter() - t0, n_dispatch * scan_chunk


def run_bench(
    per_chip_batch: int = 128,  # measured sweet spot on v5e (96/192/256 all slower, BENCHMARKS.md)
    image_size: int = 224,
    steps: int = 32,
    warmup: int = 16,
    smoke: bool = False,
    scan_chunk: int = 16,
    multihost: bool = False,
    remat: bool = False,
    grad_comms: str = "none",
) -> dict:
    """Time the ResNet-50 train step with a device-side training loop.

    ``lax.scan`` runs ``scan_chunk`` optimizer steps per dispatch — the
    idiomatic TPU training loop (host only dispatches and reads
    metrics). This matters doubly here: the axon relay adds ~6 ms of
    host→device overhead per dispatch (measured, BENCHMARKS.md
    roofline section), which a per-step Python loop pays 16× more often.
    Pass ``scan_chunk=1`` for the per-dispatch variant.

    ``grad_comms`` picks the gradient-communication schedule
    (``none`` = XLA's implicit fp32 AllReduce; ``quantized`` /
    ``zero1`` / ``quantized+zero1`` / ``overlap`` /
    ``quantized+overlap`` / ``zero2`` / ``zero3`` route through
    ``hops_tpu.parallel.grad_comms``) so the trajectory can attribute
    comms wins; the chosen mode and its compression ratio travel in
    the result. Overlap-scheduled modes (``overlap``/``zero2``/
    ``zero3``) additionally re-time the step against the matching
    compute-then-communicate schedule and a no-reduction reference to
    report ``overlap_fraction`` — the share of comms time hidden under
    backward — plus per-chip optimizer-state bytes (the ZeRO ladder's
    memory story).
    """
    import dataclasses as _dc

    from hops_tpu.models import common
    from hops_tpu.models.resnet import ResNet18ish, ResNet50
    from hops_tpu.parallel import grad_comms as gc_lib
    from hops_tpu.parallel.strategy import CollectiveAllReduceStrategy, Strategy

    gc_cfg = gc_lib.GradCommsConfig.parse(grad_comms)

    platform = jax.devices()[0].platform
    if not smoke and platform == "cpu":
        # Plumbing-validation tier: ResNet-50 at TPU sizing costs ~9 min
        # of XLA:CPU compile plus hours of stepping. The smoke path
        # already established the precedent of emitting this metric from
        # ResNet18ish on CPU; the non-smoke CPU tier does the same at a
        # slightly larger shape so every grad-comms collective still
        # runs end-to-end. There is no recorded CPU baseline, so this
        # sizing IS the CPU-platform config (the per-platform baseline
        # file keeps later runs comparable).
        per_chip_batch = min(per_chip_batch, 8)
        image_size = min(image_size, 96)
        steps, warmup, scan_chunk = min(steps, 4), min(warmup, 2), min(scan_chunk, 2)
        _note(f"cpu platform: ResNet18ish tier, batch={per_chip_batch} "
              f"image={image_size} steps={steps}")

    if smoke or platform == "cpu":
        model = ResNet18ish(dtype=jnp.float32, remat=remat)
        if smoke:
            per_chip_batch, image_size, steps, warmup, scan_chunk = 8, 32, 4, 2, 2
    else:
        model = ResNet50(num_classes=1000, remat=remat)

    scan_chunk = min(scan_chunk, steps)  # --steps 8 means 8 steps, not 16
    # --multihost: the whole-slice mesh (XLA AllReduce over ICI/DCN),
    # launched one process per host via ``python -m hops_tpu.launch``
    # (RUNBOOK_v5e64.md). Default: all chips of this host.
    strategy = CollectiveAllReduceStrategy() if multihost else Strategy()
    n_chips = strategy.num_replicas_in_sync
    global_batch = per_chip_batch * n_chips
    local_batch = per_chip_batch * (jax.local_device_count() if multihost else n_chips)
    _note(f"backend up: {n_chips} chip(s), platform={platform}")

    # Init under ONE jit at a tiny batch: params and BN stats are
    # batch-independent, and an eager init dispatches every conv as its
    # own relay compile round-trip — ~100 chances for a transient
    # UNAVAILABLE to kill the run (observed: rc=1 after 27 min inside
    # model.init, HW_MEASURE.jsonl 2026-07-31). One small compiled
    # program leaves the train-step compile as the only big request.
    import functools

    init_fn = functools.partial(
        common.create_bn_train_state,
        model,
        input_shape=(8, image_size, image_size, 3),
    )
    # One jit wrapper, hoisted: a fresh ``jax.jit(init_fn)`` per
    # remake_state call would recompile init on every transient-retry.
    jit_init = jax.jit(init_fn)

    def make_state_for(cfg):
        st = strategy.replicate(jit_init(jax.random.PRNGKey(0)))
        if cfg is not None and cfg.update_sharding == "zero3":
            # ZeRO-3 trains on the flat-shard state carrier: params and
            # moments live 1/N-sharded across the data axis at rest.
            st = gc_lib.zero3_init(st, strategy.mesh, strategy.data_axis)
        elif cfg is not None and cfg.update_sharding in (
            "cross_replica", "zero2",
        ):
            # ZeRO-1/2 persistent-sharded moments: optimizer state
            # lives 1/N-sharded between steps (params stay dense) —
            # opt_state_bytes_per_chip on the JSON line shows the ~1/N.
            st = gc_lib.zero12_init(st, strategy.mesh, cfg,
                                    strategy.data_axis)
        return st

    def build_step(cfg):
        ts = common.make_bn_train_step(grad_comms=cfg)

        def multi_step(state, batch):
            def body(st, _):
                st, metrics = ts(st, batch)
                return st, metrics["loss"]

            state, losses = jax.lax.scan(body, state, None, length=scan_chunk)
            return state, losses[-1]

        # Propagate the inner step's grad-comms marker (and the scan
        # factor, so the wire-byte counters account every fused
        # optimizer step).
        multi_step.grad_comms = cfg
        multi_step.grad_comms_steps = scan_chunk
        return strategy.step(multi_step, grad_comms=cfg)

    make_state = lambda: make_state_for(gc_cfg)  # noqa: E731
    state = make_state()
    _note("params initialized")
    step_fn = build_step(gc_cfg)
    gc_pre, gc_post = (
        gc_lib.wire_bytes(state.params, gc_cfg) if gc_cfg is not None else (0, 0)
    )
    # Read off the live initial state BEFORE the timed loop donates it
    # — re-initializing a whole state later just to count bytes would
    # double the init cost and peak memory.
    gc_opt_bytes = _opt_state_bytes(state) if gc_cfg is not None else (0, 0)

    # Each process contributes its own local shard of the global batch.
    rs = np.random.RandomState(jax.process_index())
    batch = strategy.distribute_batch(
        {
            "image": rs.randn(local_batch, image_size, image_size, 3).astype(np.float32),
            "label": rs.randint(0, 10, (local_batch,)),
        }
    )

    elapsed, total_steps = _timed_loop(
        step_fn, state, batch, steps=steps, warmup=warmup,
        scan_chunk=scan_chunk, remake_state=make_state,
    )
    samples_per_sec = global_batch * total_steps / elapsed
    result = {
        "samples_per_sec": samples_per_sec,
        "samples_per_sec_per_chip": samples_per_sec / n_chips,
        "step_time_ms": elapsed / total_steps * 1e3,
        "n_chips": n_chips,
        "global_batch": global_batch,
        "platform": jax.devices()[0].platform,
    }
    if gc_cfg is not None:
        result["grad_comms"] = gc_cfg.mode
        result["grad_comms_compression"] = round(gc_pre / gc_post, 2) if gc_post else 1.0
        result["opt_state_bytes"] = gc_opt_bytes[0]
        result["opt_state_bytes_per_chip"] = gc_opt_bytes[1]
        overlapish = gc_cfg.overlap or gc_cfg.update_sharding in ("zero2", "zero3")
        if overlapish:
            # Re-time against (a) the matching compute-then-communicate
            # schedule and (b) a no-reduction reference: the comms time
            # is (a) - (b), the hidden share is ((a) - overlap) / comms.
            seq_cfg = (
                _dc.replace(gc_cfg, overlap=False)
                if gc_cfg.overlap
                else _dc.replace(gc_cfg, update_sharding="cross_replica")
            )
            local_cfg = gc_lib.GradCommsConfig(local_only=True)
            t_overlap = elapsed / total_steps
            ref = {}
            for name, cfg in (("sequential", seq_cfg), ("local", local_cfg)):
                _note(f"overlap attribution: timing the {name} reference "
                      f"({cfg.mode})")
                el, n = _timed_loop(
                    build_step(cfg), make_state_for(cfg), batch,
                    steps=steps, warmup=warmup, scan_chunk=scan_chunk,
                    remake_state=lambda cfg=cfg: make_state_for(cfg),
                )
                ref[name] = el / n
            comms_s = max(ref["sequential"] - ref["local"], 0.0)
            hidden_s = max(ref["sequential"] - t_overlap, 0.0)
            frac = min(1.0, hidden_s / comms_s) if comms_s > 0 else 0.0
            result["overlap_fraction"] = round(frac, 4)
            result["seq_step_time_ms"] = round(ref["sequential"] * 1e3, 3)
            result["nocomms_step_time_ms"] = round(ref["local"] * 1e3, 3)
            from hops_tpu.telemetry import REGISTRY

            REGISTRY.gauge(
                "hops_tpu_grad_comms_overlap_fraction",
                "Share of gradient-comms time hidden under backward "
                "compute (bench-measured)",
                labels=("mode",),
            ).set(frac, mode=gc_cfg.mode)
    return result


def _opt_state_bytes(state) -> tuple[int, int]:
    """(total, per-chip) optimizer-state bytes: per-chip counts each
    leaf's addressable shard, so ZeRO-3's sharded-at-rest moments show
    their 1/N footprint while replicated-contract modes show the full
    one."""
    total = per_chip = 0
    for leaf in jax.tree.leaves(state.opt_state):
        itemsize = jnp.dtype(leaf.dtype).itemsize
        nbytes = leaf.size * itemsize
        total += nbytes
        shards = getattr(leaf, "addressable_shards", None)
        per_chip += shards[0].data.size * itemsize if shards else nbytes
    return int(total), int(per_chip)


def run_lm_bench(
    per_chip_batch: int = 8,
    seq_len: int = 1024,
    steps: int = 16,
    warmup: int = 8,
    smoke: bool = False,
    scan_chunk: int = 8,
    remat: bool = False,
    loss_chunk: int = 512,
) -> dict:
    """Driver-grade LM training headline: tokens/s/chip and MFU%.

    The LM stack is half the framework (flash kernels, ring/Ulysses,
    chunked xent, the serving engine) but through round 4 only ResNet
    had a driver-style number (round-4 review item #4). This times the
    full next-token training step — GPT-2-medium-class TransformerLM
    (~180M params: d_model 1024, d_head 128 per the round-4 decode
    finding, 12 layers), flash attention, token-chunked LM-head loss,
    bf16 matmuls — with the same device-side `lax.scan` loop and sync
    discipline as the ResNet bench.

    MFU uses the standard model-FLOPs accounting: 6*N_matmul per token
    for fwd+bwd over every matmul parameter (embedding lookups are
    gathers, not matmuls) plus the causal-attention term
    6 * d_model * seq * layers; remat recompute is deliberately NOT
    credited, so --remat reports honest (lower) MFU.
    """
    import functools

    from hops_tpu.models import common
    from hops_tpu.models.transformer import TransformerLM, make_lm_train_step
    from hops_tpu.parallel.strategy import Strategy

    if smoke:
        d_model, num_layers, vocab = 64, 2, 256
        per_chip_batch, seq_len, steps, warmup, scan_chunk, loss_chunk = 2, 64, 4, 2, 2, 32
    else:
        d_model, num_layers, vocab = 1024, 12, 32000

    model = TransformerLM(
        vocab_size=vocab,
        d_model=d_model,
        num_heads=8,
        num_layers=num_layers,
        dtype=jnp.bfloat16,
        attention_impl="flash",
        remat=remat,
    )
    strategy = Strategy()
    n_chips = strategy.num_replicas_in_sync
    global_batch = per_chip_batch * n_chips
    _note(f"backend up: {n_chips} chip(s), platform={jax.devices()[0].platform}")

    init_fn = functools.partial(
        common.create_train_state, model, input_shape=(1, 8), input_dtype=jnp.int32
    )
    # Hoisted jit wrapper — same recompile-on-retry fix as run_bench.
    jit_init = jax.jit(init_fn)
    make_state = lambda: strategy.replicate(jit_init(jax.random.PRNGKey(0)))  # noqa: E731
    state = make_state()
    n_params = sum(x.size for x in jax.tree.leaves(state.params))
    n_embed = state.params["embed"]["embedding"].size
    _note(f"params initialized: {n_params / 1e6:.1f}M ({(n_params - n_embed) / 1e6:.1f}M matmul)")

    train_step = make_lm_train_step(loss_chunk=loss_chunk)
    scan_chunk = min(scan_chunk, steps)

    def multi_step(state, batch):
        def body(st, _):
            st, metrics = train_step(st, batch)
            return st, metrics["loss"]

        state, losses = jax.lax.scan(body, state, None, length=scan_chunk)
        return state, losses[-1]

    step_fn = strategy.step(multi_step)
    rs = np.random.RandomState(jax.process_index())
    # seq_len + 1 ids per row: the step slices inputs[:-1] / targets[1:],
    # so the model itself runs at exactly seq_len.
    batch = strategy.distribute_batch(
        {"tokens": rs.randint(0, vocab, (global_batch, seq_len + 1)).astype(np.int32)}
    )

    elapsed, total_steps = _timed_loop(
        step_fn, state, batch, steps=steps, warmup=warmup,
        scan_chunk=scan_chunk, remake_state=make_state,
    )
    tokens_per_sec = global_batch * seq_len * total_steps / elapsed
    # Model FLOPs per trained token: 2 MACs/param fwd, 2x that bwd,
    # plus causal attention (QK^T + AV, s/2 average span): fwd
    # 2 * 2 * d * s/2 * 2 = 2*d*s per layer-token, x3 for training.
    fwd_flops_per_token = 2 * (n_params - n_embed) + 2 * d_model * seq_len * num_layers
    train_flops_per_token = 3 * fwd_flops_per_token
    achieved = tokens_per_sec / n_chips * train_flops_per_token
    platform = jax.devices()[0].platform
    # Per-generation peak from the roofline's own table — MFU against
    # the wrong generation's roof would overstate the headline. None
    # (unknown chip / cpu) means no MFU claim at all.
    from hops_tpu.runtime.diagnostics import device_peaks

    peaks = device_peaks() if platform == "tpu" else None
    peak = peaks[0] if peaks else None
    return {
        "tokens_per_sec": tokens_per_sec,
        "tokens_per_sec_per_chip": tokens_per_sec / n_chips,
        "step_time_ms": elapsed / total_steps * 1e3,
        "mfu_pct": round(100 * achieved / peak, 2) if peak else None,
        "model_tflops_per_sec_per_chip": round(achieved / 1e12, 2),
        "n_params_m": round(n_params / 1e6, 1),
        "n_chips": n_chips,
        "global_batch": global_batch,
        "seq_len": seq_len,
        "platform": platform,
    }


def run_input_pipeline_bench(
    mode: str,
    *,
    records: int = 1024,
    record_shape: tuple = (32, 32, 3),
    batch_size: int = 64,
    epochs: int = 2,
    workers: int = 6,
    queue_depth: int = 8,
    stall_ms: float = 3.0,
    consumer_ms: float = 80.0,
) -> dict:
    """Host input-pipeline bench: the decode-heavy CPU tier.

    Measures `featurestore/loader.py` end-to-end against a synthetic
    RecordIO dataset whose decode is the mix that actually dominates
    real host input at pod scale (arXiv:1909.09756): a per-record
    storage stall (emulated cold read — a GIL-free wait, exactly what
    the thread pool overlaps) plus a real zlib inflate + frombuffer
    (GIL-releasing CPU work). The consumer emulates a fast device step
    (``consumer_ms``), so the starved-step fraction means what it means
    in training: the fraction of steps where the host, not the device,
    set the pace.

    ``mode="sync"`` is the single-threaded reference
    (``num_workers=0``); ``mode="threaded"`` is the staged pipeline.
    Runs entirely host-side — no accelerator, no relay, no lock.
    """
    import tempfile
    import zlib

    from hops_tpu.featurestore.loader import DataLoader, RecordIOSource
    from hops_tpu.native.recordio import RecordWriter
    from hops_tpu.telemetry.metrics import REGISTRY

    if mode not in ("sync", "threaded"):
        raise ValueError(f"mode must be sync|threaded, got {mode!r}")

    import shutil

    tmp = Path(tempfile.mkdtemp(prefix="hops_tpu_feedbench_"))
    try:
        rs = np.random.RandomState(0)
        n_shards = 4
        paths = []
        per_shard = records // n_shards
        for s in range(n_shards):
            p = tmp / f"shard-{s:03d}.rio"
            with RecordWriter(p) as w:
                for _ in range(per_shard):
                    raw = (rs.randint(0, 255, record_shape)
                           .astype(np.float32).tobytes())
                    w.write(zlib.compress(raw, 1))
            paths.append(p)

        stall_s = stall_ms / 1e3

        def decode(raw: bytes) -> np.ndarray:
            time.sleep(stall_s)  # emulated cold-storage read latency
            return np.frombuffer(
                zlib.decompress(raw), np.float32).reshape(record_shape)

        name = f"bench-{mode}"
        loader = DataLoader(
            RecordIOSource(paths, decode=decode),
            batch_size,
            num_epochs=epochs,
            seed=0,
            num_workers=0 if mode == "sync" else workers,
            queue_depth=queue_depth,
            name=name,
        )
        consumer_s = consumer_ms / 1e3
        n_samples = steps = 0
        t0 = time.perf_counter()
        for batch in loader:
            time.sleep(consumer_s)  # the emulated device step
            n_samples += len(batch)
            steps += 1
        elapsed = time.perf_counter() - t0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    starved = REGISTRY.counter(
        "hops_tpu_feed_starved_steps_total", labels=("pipeline",),
    ).value(pipeline=name)
    # The first step has no consumer interval and is excluded from
    # starvation accounting (pipeline warm-fill), hence steps - 1.
    starved_frac = starved / max(1, steps - 1)
    return {
        "mode": mode,
        "samples_per_sec": n_samples / elapsed,
        "steps": steps,
        "starved_steps": int(starved),
        "starved_frac": round(starved_frac, 4),
        "workers": 0 if mode == "sync" else workers,
        "queue_depth": queue_depth,
        "stall_ms": stall_ms,
        "consumer_ms": consumer_ms,
    }


def run_online_store_bench(
    smoke: bool = False,
    *,
    entities: int = 4096,
    duration_s: float = 6.0,
    readers: int = 4,
    shards: int = 8,
    batch: int = 32,
    write_rps: float = 400.0,
) -> dict:
    """The ``--online-store`` tier: request-time feature joins against
    the sharded online store under concurrent write-through load.

    Host-only (no accelerator, no relay lock): two preloaded feature
    groups (users + items), a pubsub producer streaming user updates at
    ``write_rps`` rows/s, the write-through Materializer tailing the
    topic, and ``readers`` threads driving batched entity-ID joins
    through a FeatureJoinPredictor. Reports lookup QPS (point lookups
    across both groups), join p50/p99 latency, hit rate, and the
    freshness lag under that concurrent write-through — the serving-
    path numbers the online subsystem exists to hold down.
    """
    import shutil
    import tempfile
    import threading

    from hops_tpu.featurestore.online_serving import (
        FeatureJoinPredictor,
        Materializer,
        ShardedOnlineStore,
    )
    from hops_tpu.messaging import pubsub
    from hops_tpu.runtime import config as rtconfig
    from hops_tpu.telemetry.metrics import REGISTRY

    if smoke:
        entities, duration_s, readers, shards, write_rps = 256, 1.5, 2, 4, 100.0

    tmp = Path(tempfile.mkdtemp(prefix="hops_tpu_onlinebench_"))
    rtconfig.configure(workspace=str(tmp / "ws"), project="bench")
    rs = np.random.RandomState(0)
    try:
        users = ShardedOnlineStore(
            "bench_users", 1, primary_key=["user_id"], shards=shards
        )
        items = ShardedOnlineStore(
            "bench_items", 1, primary_key=["item_id"], shards=shards
        )
        n_items = max(entities // 4, 1)
        import pandas as pd

        users.put_dataframe(pd.DataFrame({
            "user_id": np.arange(entities),
            "u_clicks": rs.rand(entities),
            "u_spend": rs.rand(entities),
        }))
        items.put_dataframe(pd.DataFrame({
            "item_id": np.arange(n_items),
            "i_price": rs.rand(n_items),
            "i_rank": rs.rand(n_items),
        }))

        topic = "bench-users-updates"
        pubsub.create_topic(topic)
        daemon = Materializer(
            users, topic, event_time="event_time", poll_interval_s=0.005
        ).start()

        stop = threading.Event()

        def write_through() -> None:
            prod = pubsub.Producer(topic)
            wrs = np.random.RandomState(1)
            period = 1.0 / write_rps
            while not stop.is_set():
                uid = int(wrs.randint(0, entities))
                prod.send({
                    "user_id": uid,
                    "u_clicks": float(wrs.rand()),
                    "u_spend": float(wrs.rand()),
                    "event_time": time.time(),
                })
                stop.wait(period)

        predictor = FeatureJoinPredictor(
            lambda vectors: vectors,
            {
                "groups": [
                    {"name": "bench_users", "version": 1,
                     "primary_key": ["user_id"],
                     "features": ["u_clicks", "u_spend"]},
                    {"name": "bench_items", "version": 1,
                     "primary_key": ["item_id"],
                     "features": ["i_price", "i_rank"]},
                ],
                "missing": "default",
                "shards": shards,
            },
            model="bench",
            stores={"bench_users": users, "bench_items": items},
        )

        lookup_counter = REGISTRY.counter(
            "hops_tpu_online_lookup_total", labels=("store", "result"))

        def lookups(result: str) -> float:
            return sum(
                lookup_counter.value(store=s, result=result)
                for s in ("bench_users_1", "bench_items_1")
            )

        base = {r: lookups(r) for r in ("hit", "miss", "expired", "error")}
        lat_lock = threading.Lock()
        join_lat: list[float] = []  # guarded by: lat_lock

        def reader(seed: int) -> None:
            rrs = np.random.RandomState(100 + seed)
            while not stop.is_set():
                entries = [
                    {"user_id": int(rrs.randint(0, int(entities * 1.02))),
                     "item_id": int(rrs.randint(0, n_items))}
                    for _ in range(batch)
                ]
                t0 = time.perf_counter()
                predictor.predict(entries)
                dt = time.perf_counter() - t0
                with lat_lock:
                    join_lat.append(dt)

        writer = threading.Thread(target=write_through, daemon=True)
        threads = [writer] + [
            threading.Thread(target=reader, args=(i,), daemon=True)
            for i in range(readers)
        ]
        t_start = time.perf_counter()
        for t in threads:
            t.start()
        time.sleep(duration_s)
        stop.set()
        for t in threads:
            t.join(timeout=5)
        wall = time.perf_counter() - t_start
        daemon_lag = users.freshness_lag_s()
        daemon.stop()

        after = {r: lookups(r) for r in ("hit", "miss", "expired", "error")}
        delta = {r: after[r] - base[r] for r in after}
        total = sum(delta.values())
        lat_ms = np.asarray(join_lat) * 1e3
        materialized = REGISTRY.counter(
            "hops_tpu_online_materialized_rows_total", labels=("store",)
        ).value(store="bench_users_1")
        users.close()
        items.close()
        return {
            "lookup_qps": total / wall,
            "join_p50_ms": round(float(np.percentile(lat_ms, 50)), 3) if len(lat_ms) else 0.0,
            "join_p99_ms": round(float(np.percentile(lat_ms, 99)), 3) if len(lat_ms) else 0.0,
            "hit_rate": round(delta["hit"] / max(total, 1), 4),
            "freshness_lag_s": round(daemon_lag, 4),
            "materialized_rows": int(materialized),
            "requests": len(join_lat),
            "entities": entities,
            "shards": shards,
            "readers": readers,
            "batch": batch,
            "write_rps": write_rps,
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def run_serving_fleet_bench(
    smoke: bool = False,
    *,
    replicas: int = 3,
    clients: int = 8,
    work_ms: float = 60.0,
    baseline_s: float = 3.0,
    steady_s: float = 4.0,
) -> dict:
    """The ``--serving-fleet`` tier: N replicas behind the fleet router
    vs one, under closed-loop client load, with a mid-load rollout.

    Host-only (no accelerator, no relay lock). The predictor stands in
    for a single-accelerator model: each replica serializes its
    requests behind its own lock for ``work_ms`` (sleep releases the
    GIL, so in-process replicas genuinely run concurrently). Phases:

    1. **baseline** — a 1-replica fleet, ``clients`` closed-loop
       threads: the single-endpoint ceiling (~1000/work_ms rps).
    2. **scale-up** — a fresh fleet starting at 1 replica with an
       aggressive autoscaler (max = ``replicas``): the load drives it
       to the ceiling and the scale events land on the counter.
    3. **steady state** — requests/s, p50/p99 latency, and per-replica
       forward balance over ``steady_s`` at full size.
    4. **rollout** — ``roll_out`` to an identical v2 mid-load; the
       blip is the longest gap between consecutive successful
       completions while the rollout ran (zero-downtime means it stays
       at request scale, not drain scale).

    Every client records errors; the tier asserts none in its JSON.
    """
    import shutil
    import tempfile
    import threading

    from hops_tpu.modelrepo import fleet, registry, serving
    from hops_tpu.modelrepo.fleet.autoscale import AutoscalePolicy
    from hops_tpu.runtime import config as rtconfig
    from hops_tpu.telemetry.metrics import REGISTRY

    if smoke:
        replicas, clients, work_ms = 2, 4, 3.0
        baseline_s, steady_s = 0.8, 1.0

    tmp = Path(tempfile.mkdtemp(prefix="hops_tpu_fleetbench_"))
    rtconfig.configure(workspace=str(tmp / "ws"), project="bench")
    try:
        art = tmp / "art"
        art.mkdir()
        (art / "p.py").write_text(
            "import threading, time\n"
            "class Predict:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def predict(self, instances):\n"
            "        with self._lock:\n"
            f"            time.sleep({work_ms / 1e3})\n"
            "        return [[v[0]] for v in instances]\n"
        )
        registry.export(art, "fleetbench", metrics={"v": 1.0})
        v2 = registry.export(art, "fleetbench", metrics={"v": 2.0})["version"]
        serving.create_or_update("fleetbench", model_name="fleetbench",
                                 model_version=1, model_server="PYTHON")

        class _Load:
            """Closed-loop clients; thread-safe completion log."""

            def __init__(self, f, n):
                self.f = f
                self.errors = 0
                self.lock = threading.Lock()
                self.done: list[tuple[float, float]] = []  # (t_done, latency)
                self.stop = threading.Event()
                self.threads = [
                    threading.Thread(target=self._run, daemon=True)
                    for _ in range(n)
                ]
                for t in self.threads:
                    t.start()

            def _run(self):
                while not self.stop.is_set():
                    t0 = time.perf_counter()
                    try:
                        self.f.predict([[1]], timeout_s=30.0)
                        t1 = time.perf_counter()
                        with self.lock:
                            self.done.append((t1, t1 - t0))
                    except Exception:  # noqa: BLE001 — counted, asserted on
                        with self.lock:
                            self.errors += 1

            def halt(self):
                self.stop.set()
                for t in self.threads:
                    t.join(timeout=10)

            def window(self, t_from, t_to):
                with self.lock:
                    return [(t, lat) for t, lat in self.done
                            if t_from <= t <= t_to]

        # -- phase 1: single-replica baseline --------------------------------
        with fleet.start_fleet("fleetbench", 1, inprocess=True,
                               scrape_interval_s=0.05) as f1:
            load = _Load(f1, clients)
            time.sleep(baseline_s)
            t_to = time.perf_counter()
            load.halt()
            base_done = load.window(t_to - baseline_s * 0.7, t_to)
            single_rps = len(base_done) / (baseline_s * 0.7)
            base_errors = load.errors

        # -- phases 2-4: autoscaled fleet, steady state, rollout -------------
        policy = AutoscalePolicy(
            min_replicas=1, max_replicas=replicas, target_load=2.0,
            breaches_to_scale=2, up_cooldown_s=0.2, down_cooldown_s=60.0,
        )
        scale_counter = REGISTRY.counter(
            "hops_tpu_fleet_scale_events_total", labels=("model", "direction"))
        ups0 = scale_counter.value(model="fleetbench", direction="up")
        forwards = REGISTRY.counter(
            "hops_tpu_fleet_forwards_total", labels=("model", "replica"))
        with fleet.start_fleet("fleetbench", 1, inprocess=True,
                               scrape_interval_s=0.05, autoscale=policy,
                               autoscale_interval_s=0.05) as f:
            load = _Load(f, clients)
            # Wait for the autoscaler to reach full size under load.
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if len(f.manager.ready()) >= replicas:
                    break
                time.sleep(0.05)
            scaled_to = len(f.manager.ready())
            # Steady-state window.
            rids = [r.rid for r in f.manager.ready()]
            fwd0 = {rid: forwards.value(model="fleetbench", replica=rid)
                    for rid in rids}
            t_from = time.perf_counter()
            time.sleep(steady_s)
            t_to = time.perf_counter()
            fwd1 = {rid: forwards.value(model="fleetbench", replica=rid)
                    for rid in rids}
            steady = load.window(t_from, t_to)
            lat_ms = np.asarray([lat for _, lat in steady]) * 1e3
            shares = [fwd1[r] - fwd0[r] for r in rids]
            balance = (min(shares) / max(shares)) if min(shares) >= 0 and max(shares) > 0 else 0.0
            # Mid-load rollout to v2.
            t_roll0 = time.perf_counter()
            summary = f.roll_out(v2, canary_requests=4, canary_window_s=20)
            t_roll1 = time.perf_counter()
            time.sleep(0.2)
            load.halt()
            roll_done = sorted(t for t, _ in load.window(t_roll0, t_roll1 + 0.2))
            blip_ms = 0.0
            if len(roll_done) >= 2:
                blip_ms = max(b - a for a, b in zip(roll_done, roll_done[1:])) * 1e3
            errors = load.errors + base_errors
        ups = scale_counter.value(model="fleetbench", direction="up") - ups0
        fleet_rps = len(steady) / (t_to - t_from)
        return {
            "requests_per_sec": round(fleet_rps, 1),
            "p50_ms": round(float(np.percentile(lat_ms, 50)), 2) if len(lat_ms) else 0.0,
            "p99_ms": round(float(np.percentile(lat_ms, 99)), 2) if len(lat_ms) else 0.0,
            "replicas": scaled_to,
            "clients": clients,
            "work_ms": work_ms,
            "balance_min_over_max": round(balance, 3),
            "scale_events_up": int(ups),
            "rollout_outcome": summary["outcome"],
            "rollout_duration_s": summary["duration_s"],
            "rollout_blip_ms": round(blip_ms, 1),
            "errors": int(errors),
            "single_replica_rps": round(single_rps, 1),
            "speedup_vs_single": round(fleet_rps / max(single_rps, 1e-9), 2),
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def run_multi_host_bench(
    smoke: bool = False,
    *,
    hosts: int = 2,
    replicas: int = 2,
    shards: int = 2,
    clients: int = 6,
    work_ms: float = 15.0,
    measure_s: float = 3.0,
    entities: int = 2000,
    lookup_batches: int = 200,
    batch_keys: int = 64,
) -> dict:
    """The ``--multi-host`` tier: hostd-placed serving and placed
    feature shards vs their local-placement baselines.

    Host-only (no accelerator, no relay lock; the hostds run
    ``inprocess_units=True`` — the placement *control plane* is the
    real HTTP surface under test, the units skip process startup so
    the tier measures placement, not fork+import). Phases:

    1. **local fleet** — ``replicas`` in-process replicas behind the
       router, closed-loop clients for ``measure_s``: the
       local-placement baseline (rps, p50/p99).
    2. **placed fleet** — the same fleet with ``placement=`` a
       :class:`PlacementClient` over ``hosts`` hostd agents: identical
       load. Since placement is control-plane-only (the router talks
       straight to each replica's registered host:port), the ratio to
       phase 1 is the data-plane-unchanged check; the JSON also
       carries the control-plane RPC count that placed the fleet.
    3. **shard fan-out** — ``batch_keys``-key ``multi_get`` batches
       against a local ``ShardedOnlineStore`` vs the same data behind
       ``shards`` placed shard servers (warm-started from one
       snapshot): lookups/s and per-batch p50/p99 for both, plus a
       row-identity check — the placed store must return exactly the
       local store's rows.

    Every client records errors; the tier asserts none in its JSON.
    """
    import shutil
    import tempfile
    import threading

    import pandas as pd

    from hops_tpu.featurestore.online_serving import ShardedOnlineStore
    from hops_tpu.jobs import placement
    from hops_tpu.modelrepo import fleet, registry, serving
    from hops_tpu.runtime import config as rtconfig
    from hops_tpu.telemetry.metrics import REGISTRY

    if smoke:
        clients, work_ms, measure_s = 4, 3.0, 1.0
        entities, lookup_batches, batch_keys = 400, 60, 32

    tmp = Path(tempfile.mkdtemp(prefix="hops_tpu_mhbench_"))
    rtconfig.configure(workspace=str(tmp / "ws"), project="bench")
    hostds: list = []
    stores: list = []
    try:
        art = tmp / "art"
        art.mkdir()
        (art / "p.py").write_text(
            "import threading, time\n"
            "class Predict:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def predict(self, instances):\n"
            "        with self._lock:\n"
            f"            time.sleep({work_ms / 1e3})\n"
            "        return [[v[0]] for v in instances]\n"
        )
        registry.export(art, "mhbench", metrics={"v": 1.0})
        serving.create_or_update("mhbench", model_name="mhbench",
                                 model_version=1, model_server="PYTHON")

        class _Load:
            """Closed-loop clients; thread-safe completion log."""

            def __init__(self, f, n):
                self.f = f
                self.errors = 0
                self.lock = threading.Lock()
                self.lat: list[float] = []
                self.stop = threading.Event()
                self.threads = [
                    threading.Thread(target=self._run, daemon=True)
                    for _ in range(n)
                ]
                for t in self.threads:
                    t.start()

            def _run(self):
                while not self.stop.is_set():
                    t0 = time.perf_counter()
                    try:
                        self.f.predict([[1]], timeout_s=30.0)
                        with self.lock:
                            self.lat.append(time.perf_counter() - t0)
                    except Exception:  # noqa: BLE001 — counted, asserted on
                        with self.lock:
                            self.errors += 1

            def halt(self):
                self.stop.set()
                for t in self.threads:
                    t.join(timeout=10)

        def _serve_phase(**fleet_kwargs):
            with fleet.start_fleet("mhbench", replicas,
                                   scrape_interval_s=0.05,
                                   **fleet_kwargs) as f:
                load = _Load(f, clients)
                t0 = time.perf_counter()
                time.sleep(measure_s)
                elapsed = time.perf_counter() - t0
                load.halt()
                lat_ms = np.asarray(load.lat) * 1e3
                return {
                    "rps": round(len(load.lat) / elapsed, 1),
                    "p50_ms": round(float(np.percentile(lat_ms, 50)), 2) if len(lat_ms) else 0.0,
                    "p99_ms": round(float(np.percentile(lat_ms, 99)), 2) if len(lat_ms) else 0.0,
                    "errors": load.errors,
                }

        # -- phase 1: local-placement baseline -------------------------------
        local_serve = _serve_phase(inprocess=True)

        # -- phase 2: hostd-placed fleet --------------------------------------
        for i in range(hosts):
            hostds.append(placement.Hostd(
                f"bench-h{i}", inprocess_units=True,
                unit_root=tmp / f"h{i}"))
        client = placement.PlacementClient(placement.HostRegistry(
            hosts=[h.host() for h in hostds]))
        m_rpc = REGISTRY.counter(
            "hops_tpu_placement_rpc_total",
            labels=("host", "verb", "outcome"))
        rpc0 = sum(
            m_rpc.value(host=h.name, verb=v, outcome="ok")
            for h in hostds for v in ("spawn", "drain", "reap", "health"))
        placed_serve = _serve_phase(placement=client)
        placed_rpcs = sum(
            m_rpc.value(host=h.name, verb=v, outcome="ok")
            for h in hostds for v in ("spawn", "drain", "reap", "health")
        ) - rpc0

        # -- phase 3: shard fan-out, local vs placed --------------------------
        rows = pd.DataFrame({
            "uid": list(range(entities)),
            "score": [i * 0.5 for i in range(entities)],
            "clicks": [i % 97 for i in range(entities)],
        })
        local_store = ShardedOnlineStore(
            "mhbench_feats", primary_key=["uid"], shards=shards,
            root=tmp / "online")
        stores.append(local_store)
        local_store.put_dataframe(rows)
        snap = local_store.snapshot(tmp / "snap")

        rng = np.random.default_rng(7)
        batches = [
            [[int(k)] for k in rng.integers(0, entities, size=batch_keys)]
            for _ in range(lookup_batches)
        ]

        def _lookup_phase(store):
            lat = []
            t0 = time.perf_counter()
            for b in batches:
                s = time.perf_counter()
                store.multi_get(b)
                lat.append(time.perf_counter() - s)
            elapsed = time.perf_counter() - t0
            lat_ms = np.asarray(lat) * 1e3
            return {
                "lookups_per_sec": round(
                    lookup_batches * batch_keys / elapsed, 1),
                "batch_p50_ms": round(float(np.percentile(lat_ms, 50)), 2),
                "batch_p99_ms": round(float(np.percentile(lat_ms, 99)), 2),
            }

        local_lookup = _lookup_phase(local_store)
        units = [
            client.spawn("shard", {
                "store": "mhbench_feats", "version": 1, "shard_index": i,
                "shards": shards, "primary_key": ["uid"],
                "root": str(tmp / f"placed_shard{i}"), "port": 0,
                "snapshot": str(snap),
            })
            for i in range(shards)
        ]
        placed_store = ShardedOnlineStore(
            "mhbench_feats", primary_key=["uid"],
            endpoints=[f"http://{u.address}:{u.port}" for u in units])
        stores.append(placed_store)
        placed_lookup = _lookup_phase(placed_store)
        # Bit-identical serving data: the warm-started placed shards
        # must answer exactly what the local store answers.
        probe = batches[0]
        rows_match = local_store.multi_get(probe) == placed_store.multi_get(probe)
        for u in units:
            client.reap(u)

        return {
            "hosts": hosts,
            "replicas": replicas,
            "shards": shards,
            "local_rps": local_serve["rps"],
            "placed_rps": placed_serve["rps"],
            "placed_over_local": round(
                placed_serve["rps"] / max(local_serve["rps"], 1e-9), 2),
            "local_p99_ms": local_serve["p99_ms"],
            "placed_p99_ms": placed_serve["p99_ms"],
            "placement_rpcs": int(placed_rpcs),
            "local_lookups_per_sec": local_lookup["lookups_per_sec"],
            "placed_lookups_per_sec": placed_lookup["lookups_per_sec"],
            "local_batch_p99_ms": local_lookup["batch_p99_ms"],
            "placed_batch_p99_ms": placed_lookup["batch_p99_ms"],
            "rows_match": bool(rows_match),
            "errors": int(local_serve["errors"] + placed_serve["errors"]),
        }
    finally:
        for s in stores:
            try:
                s.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        for h in hostds:
            h.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def run_partition_bench(
    smoke: bool = False,
    *,
    clients: int = 4,
    work_ms: float = 5.0,
    measure_pad_s: float = 0.3,
    heartbeat_s: float = 0.15,
    lease_ttl_s: float = 0.6,
    entities: int = 200,
) -> dict:
    """The ``--partition`` tier: the headline partition-tolerance chaos
    drill, with MTTR decomposed into its control-plane phases.

    Two hostd-backed hosts carry a 2-replica placed fleet and a placed
    feature-shard pair, under closed-loop predict clients and a lookup
    loop. Then, deterministically (``faultinject.cut`` at the
    ``transport.send`` seam):

    **Leg A — zombie re-place.** Cut all traffic TO the victim host
    (its own egress stays up, so its lease keeps renewing — the worst
    case: a healthy-feeling host nobody can reach). The reconcile sweep
    finds the replica unreachable, bumps its slot's generation (the
    fence) and the autoscaler re-places on the survivor
    (``time_to_replace_s``). Heal the cut and probe the still-running
    zombie with a request stamped at the slot's CURRENT generation: it
    must answer the typed 410 (``heal_to_zombie_reject_s``), and the
    sweep then reaps it. A placed shard on the victim is superseded the
    same way and must 410 a stamped lookup (miss-degrade, no breaker
    strike).

    **Leg B — lease fence.** Cut the victim's egress too: announces
    stop landing, the lease runs out, and the hostd self-fences —
    drains and kills its own units (``time_to_fence_s``).

    Throughout: ZERO client-visible errors (the router retries around
    the cut; lookups degrade to misses), and the flight-event record
    must pass the slot invariant audit (at most one live unit per
    slot). Both are asserted, not just reported.
    """
    import shutil
    import tempfile
    import threading

    import pandas as pd

    from hops_tpu.featurestore.online import _key_of
    from hops_tpu.featurestore.online_serving import (
        ShardedOnlineStore, _shard_of)
    from hops_tpu.jobs import placement
    from hops_tpu.jobs.placement.invariants import audit
    from hops_tpu.modelrepo import fleet, registry, serving
    from hops_tpu.modelrepo.fleet.autoscale import AutoscalePolicy
    from hops_tpu.runtime import config as rtconfig, faultinject, flight
    from hops_tpu.runtime.httpclient import HTTPPool

    if smoke:
        clients, work_ms, entities = 2, 2.0, 80

    tmp = Path(tempfile.mkdtemp(prefix="hops_tpu_partbench_"))
    rtconfig.configure(workspace=str(tmp / "ws"), project="bench")
    seq0 = flight.FLIGHT.seq
    hostds: list = []
    stores: list = []
    load = None
    lookup_stop = threading.Event()
    lookup_thread = None
    client = None
    try:
        art = tmp / "art"
        art.mkdir()
        (art / "p.py").write_text(
            "import time\n"
            "class Predict:\n"
            "    def predict(self, instances):\n"
            f"        time.sleep({work_ms / 1e3})\n"
            "        return [[v[0]] for v in instances]\n"
        )
        registry.export(art, "partbench", metrics={"v": 1.0})
        serving.create_or_update("partbench", model_name="partbench",
                                 model_version=1, model_server="PYTHON")

        announce = tmp / "announce"
        for i in range(2):
            hostds.append(placement.Hostd(
                f"h{i}", inprocess_units=True, unit_root=tmp / f"h{i}",
                announce_dir=announce, heartbeat_s=heartbeat_s,
                lease_ttl_s=lease_ttl_s))
        client = placement.PlacementClient(placement.HostRegistry(
            announce_dir=announce, ttl_s=10 * lease_ttl_s))

        class _Load:
            def __init__(self, f, n):
                self.f = f
                self.errors = 0
                self.ok = 0
                self.lock = threading.Lock()
                self.stop = threading.Event()
                self.threads = [
                    threading.Thread(target=self._run, daemon=True)
                    for _ in range(n)
                ]
                for t in self.threads:
                    t.start()

            def _run(self):
                while not self.stop.is_set():
                    try:
                        self.f.predict([[1]], timeout_s=30.0)
                        with self.lock:
                            self.ok += 1
                    except Exception:  # noqa: BLE001 — counted, asserted zero
                        with self.lock:
                            self.errors += 1

            def halt(self):
                self.stop.set()
                for t in self.threads:
                    t.join(timeout=10)

        def _wait(cond, budget_s, what):
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < budget_s:
                if cond():
                    return time.perf_counter() - t0
                time.sleep(0.02)
            raise RuntimeError(f"partition bench: {what} did not happen "
                               f"within {budget_s}s")

        with fleet.start_fleet(
            "partbench", 2, placement=client,
            autoscale=AutoscalePolicy(min_replicas=2, max_replicas=3,
                                      up_cooldown_s=0.1),
            autoscale_interval_s=0.2, scrape_interval_s=0.05,
        ) as f:
            # Placed feature shards (one ends up on each host).
            rows = pd.DataFrame({
                "uid": list(range(entities)),
                "score": [i * 0.5 for i in range(entities)],
            })
            shard_units = [
                client.spawn("shard", {
                    "store": "partfeats", "version": 1, "shard_index": i,
                    "shards": 2, "primary_key": ["uid"],
                    "root": str(tmp / f"shard{i}"), "port": 0,
                })
                for i in range(2)
            ]
            store = ShardedOnlineStore(
                "partfeats", primary_key=["uid"], units=shard_units,
                placement=client, root=tmp / "online",
                breaker_reset_s=0.25)
            stores.append(store)
            store.put_dataframe(rows)
            lookup_errors = [0]

            def _lookups():
                i = 0
                while not lookup_stop.is_set():
                    try:
                        store.multi_get([[i % entities], [(i + 7) % entities]])
                    except Exception:  # noqa: BLE001 — counted, asserted zero
                        lookup_errors[0] += 1
                    i += 1
                    time.sleep(0.01)

            lookup_thread = threading.Thread(target=_lookups, daemon=True)
            lookup_thread.start()
            load = _Load(f, clients)
            time.sleep(measure_pad_s)  # steady-state traffic before the cut

            # -- leg A: asymmetric cut -> fence by generation -> re-place
            victim_rep = next(r for r in f.manager.ready()
                              if r.unit is not None)
            victim = victim_rep.unit.host.name
            zombie = victim_rep.unit  # survives rep.unit = None
            faultinject.cut(victim)
            t_cut = time.perf_counter()
            _wait(
                lambda: (client.current_generation(zombie.slot)
                         > zombie.generation
                         and len([r for r in f.manager.ready()
                                  if r.unit is not None
                                  and r.unit.host.name != victim]) >= 2),
                30.0, "generation bump + re-place on the survivor")
            time_to_replace = time.perf_counter() - t_cut

            faultinject.heal(victim)
            t_heal = time.perf_counter()
            pool = HTTPPool(identity="bench")
            token = f"{zombie.slot}:{client.current_generation(zombie.slot)}"
            zombie_outcome = None
            while time.perf_counter() - t_heal < 10.0:
                try:
                    code, _, _ = pool.request(
                        "POST",
                        f"http://{zombie.address}:{zombie.port}"
                        "/v1/models/partbench:predict",
                        b'{"instances": [[1]]}',
                        {"Content-Type": "application/json",
                         "X-Hops-Generation": token},
                        timeout_s=2.0)
                except OSError:
                    zombie_outcome = "reaped"  # sweep got there first
                    break
                if code == 410:
                    zombie_outcome = "rejected"
                    break
                time.sleep(0.02)
            heal_to_zombie_reject = time.perf_counter() - t_heal
            pool.close()
            if zombie_outcome is None:
                raise RuntimeError("partition bench: healed zombie neither "
                                   "410'd a stamped request nor was reaped")
            # The sweep must reap the superseded worker either way.
            _wait(lambda: all(u.slot != zombie.slot
                              for h in hostds if h.name == victim
                              for u in h.units()),
                  15.0, "zombie reap after heal")

            # Shard half of the fence: supersede the victim's shard and
            # prove a stamped lookup 410s (miss, no breaker strike).
            shard_rejected = None
            vic_shard = next((u for u in shard_units
                              if u.host.name == victim), None)
            if vic_shard is not None:
                client.bump_generation(vic_shard.slot)
                idx = shard_units.index(vic_shard)
                key = next(k for k in range(entities)
                           if _shard_of(_key_of([k]), 2) == idx)
                seq_shard = flight.FLIGHT.seq
                # The leg-A cut fed this shard's breaker; retry past
                # its (shortened) reset so the stamped lookup actually
                # reaches the superseded server.
                t_sh = time.perf_counter()
                while time.perf_counter() - t_sh < 5.0:
                    got = store.multi_get([[key]])
                    if (got == [None]
                            and flight.FLIGHT.events("generation_rejected",
                                                     after_seq=seq_shard)):
                        shard_rejected = True
                        break
                    time.sleep(0.05)
                else:
                    shard_rejected = False

            # -- leg B: full cut -> lease starves -> self-fence ---------
            seq_b = flight.FLIGHT.seq
            faultinject.cut(victim)
            faultinject.cut(f"{victim}->*")
            t_cut_b = time.perf_counter()
            _wait(lambda: flight.FLIGHT.events("fence", after_seq=seq_b),
                  30 * lease_ttl_s, "lease-expiry self-fence")
            time_to_fence = time.perf_counter() - t_cut_b
            fence_event = flight.FLIGHT.events("fence", after_seq=seq_b)[0]
            faultinject.heal()
            time.sleep(measure_pad_s)  # healed steady state before halt

            load.halt()
            lookup_stop.set()
            lookup_thread.join(timeout=10)

            violations = audit(after_seq=seq0)
            errors = load.errors + lookup_errors[0]
            if errors:
                raise RuntimeError(
                    f"partition bench: {load.errors} client + "
                    f"{lookup_errors[0]} lookup errors (must be zero)")
            if violations:
                raise RuntimeError(
                    f"partition bench: slot-invariant audit failed: "
                    f"{violations}")

            return {
                "victim": victim,
                "time_to_replace_s": round(time_to_replace, 3),
                "heal_to_zombie_reject_s": round(heal_to_zombie_reject, 3),
                "zombie_outcome": zombie_outcome,
                "shard_generation_rejected": shard_rejected,
                "time_to_fence_s": round(time_to_fence, 3),
                "lease_ttl_s": lease_ttl_s,
                "fence_reaped_units": len(
                    fence_event.get("data", {}).get("units", [])),
                "requests_ok": load.ok,
                "errors": 0,
                "audit_violations": 0,
            }
    finally:
        faultinject.heal()
        if load is not None:
            load.halt()
        lookup_stop.set()
        if lookup_thread is not None:
            lookup_thread.join(timeout=10)
        for s in stores:
            try:
                s.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        if client is not None:
            client.close()
        for h in hostds:
            h.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def run_tail_bench(
    smoke: bool = False,
    *,
    replicas: int = 3,
    rate_rps: float = 64.0,
    seconds: float = 7.0,
    warmup_s: float = 1.5,
    work_ms: float = 8.0,
    slow_ms: float = 250.0,
    shards: int = 4,
    slow_shard_ms: float = 0.12,
    qos_work_ms: float = 40.0,
    qos_batch_rate: float = 60.0,
    qos_interactive_rate: float = 12.0,
) -> dict:
    """The ``--tail`` tier: gray-failure tolerance under Poisson load.

    Three host-only phases (docs/operations.md "Tail latency & QoS"):

    1. **slow feature shard** — ``multi_get`` against a sharded store
       with one shard made intermittently slow (``shard.lookup``
       latency fault keyed by shard index): sequential probing vs
       parallel fan-out + straggler hedging, p50/p99 per call.
    2. **gray replica, hedged vs not** — a fleet with one replica made
       slow-not-dead (``serving.handle`` latency fault keyed by its
       port), open-loop Poisson clients. Bare fleet (no hedging, no
       ejection) vs the tail-robustness layer (adaptive hedging +
       outlier ejection): p50/p99/p999, hedge budget spend, ejections.
       The acceptance gate: hedged p99 >= 2x better at hedge rate <= 5%
       (+ the small budget burst), zero client-visible errors in both.
    3. **QoS under overload** — batch-class flood + interactive trickle
       against a smaller fleet with class limits, batch admission
       fraction, and an SLO-burn brownout: per-class latency and the
       shed mix (batch sheds first; interactive errors stay zero).

    One JSON line, like every tier.
    """
    import shutil
    import tempfile
    import threading
    from concurrent.futures import ThreadPoolExecutor

    from hops_tpu.featurestore.online_serving import ShardedOnlineStore
    from hops_tpu.modelrepo import fleet, registry, serving
    from hops_tpu.runtime import config as rtconfig
    from hops_tpu.runtime import faultinject
    from hops_tpu.runtime.httpclient import HTTPPool
    from hops_tpu.telemetry.metrics import REGISTRY

    if smoke:
        rate_rps, seconds, warmup_s = 48.0, 2.5, 1.2
        work_ms, slow_ms = 6.0, 180.0
        qos_work_ms, qos_batch_rate, qos_interactive_rate = 60.0, 40.0, 10.0

    rng = np.random.default_rng(7)

    def pctl(xs, q):
        return round(float(np.percentile(np.asarray(xs), q)), 2) if len(xs) else 0.0

    tmp = Path(tempfile.mkdtemp(prefix="hops_tpu_tailbench_"))
    rtconfig.configure(workspace=str(tmp / "ws"), project="bench")
    faultinject.disarm()
    try:
        # -- phase 1: slow feature shard, sequential vs fan-out+hedge --------
        def store_phase(fanout: bool) -> tuple[list, ShardedOnlineStore]:
            s = ShardedOnlineStore(
                f"tailfeat_{int(fanout)}", 1, primary_key=["user_id"],
                shards=shards, root=tmp / f"store{int(fanout)}",
                fanout=fanout, hedge=True,
            )
            import pandas as pd
            s.put_dataframe(pd.DataFrame(
                {"user_id": range(64), "f0": range(64)}))
            entries = [{"user_id": int(i)} for i in range(16)]
            for _ in range(24):  # warm the hedge timer's p95 history
                s.multi_get(entries)
            # Intermittently gray shard: p=0.5 so the hedge's second
            # attempt usually lands fast while the first stalls.
            faultinject.arm(
                f"shard.lookup=latency:{slow_shard_ms}@key=1,p=0.4,seed=3")
            lats = []
            calls = 64 if not smoke else 32
            for _ in range(calls):
                t0 = time.perf_counter()
                rows = s.multi_get(entries, deadline_s=2.0)
                lats.append((time.perf_counter() - t0) * 1e3)
                assert all(r is not None for r in rows)
            faultinject.disarm()
            return lats, s

        seq_lats, s1 = store_phase(fanout=False)
        s1.close()
        hedge_counter = REGISTRY.counter(
            "hops_tpu_online_shard_hedges_total", labels=("store",))
        fan_lats, s2 = store_phase(fanout=True)
        store_hedges = hedge_counter.value(store=s2.label)
        s2.close()

        # -- shared fleet scaffolding -----------------------------------------
        art = tmp / "art"
        art.mkdir()
        (art / "p.py").write_text(
            "import threading, time\n"
            "class Predict:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def predict(self, instances):\n"
            "        with self._lock:\n"
            f"            time.sleep({work_ms / 1e3})\n"
            "        return [[v[0]] for v in instances]\n"
        )
        registry.export(art, "tailbench", metrics={"v": 1.0})
        # The 24-deep cap bounds how much work can pile onto the gray
        # replica before its own shedder turns excess into
        # retry-elsewhere (a 503 the router absorbs, never the client)
        # — without a cap the pile itself becomes the tail.
        serving.create_or_update(
            "tailbench", model_name="tailbench", model_version=1,
            model_server="PYTHON",
            resilience_config={"max_inflight": 24},
        )
        # The QoS phase gets a SLOWER model so overload is bounded by
        # modeled capacity (2 replicas x 1000/qos_work_ms rps), not by
        # this box's CPUs — melting the host would measure the host.
        qart = tmp / "qart"
        qart.mkdir()
        (qart / "p.py").write_text(
            "import threading, time\n"
            "class Predict:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def predict(self, instances):\n"
            "        with self._lock:\n"
            f"            time.sleep({qos_work_ms / 1e3})\n"
            "        return [[v[0]] for v in instances]\n"
        )
        registry.export(qart, "tailqos", metrics={"v": 1.0})
        # Deliberately LOOSE static layers (generous admit fraction)
        # so the flood genuinely burns the SLO and the brownout ladder
        # is the mechanism that restores it — the phase demonstrates
        # the backstop, not the bucket.
        serving.create_or_update(
            "tailqos", model_name="tailqos", model_version=1,
            model_server="PYTHON",
            resilience_config={"max_inflight": 12, "batch_admit_frac": 0.75},
        )

        class _OpenLoop:
            """Open-loop Poisson client: arrivals fire on schedule
            whether or not earlier requests returned (the load shape
            that actually exposes tails)."""

            def __init__(self, endpoint: str, workers: int = 96):
                self.endpoint = endpoint
                self.pool = HTTPPool(max_idle_per_host=workers)
                self.ex = ThreadPoolExecutor(max_workers=workers)
                self.lock = threading.Lock()
                self.lat_ms: list[float] = []
                self.sheds = 0
                self.errors = 0

            def _one(self, headers: dict) -> None:
                t0 = time.perf_counter()
                try:
                    code, _, _ = self.pool.request(
                        "POST", self.endpoint + "/predict",
                        body=b'{"instances": [[1]]}',
                        headers={"Content-Type": "application/json",
                                 **headers},
                        timeout_s=30.0,
                    )
                except OSError:
                    code = -1
                dt = (time.perf_counter() - t0) * 1e3
                with self.lock:
                    if code == 200:
                        self.lat_ms.append(dt)
                    elif code in (429, 503):
                        self.sheds += 1
                    else:
                        self.errors += 1

            def run(self, rate: float, length_s: float,
                    headers: dict | None = None) -> None:
                """Blocks for ~length_s, firing Poisson arrivals."""
                headers = headers or {}
                t = 0.0
                t_start = time.perf_counter()
                while t < length_s:
                    t += float(rng.exponential(1.0 / rate))
                    lag = t_start + t - time.perf_counter()
                    if lag > 0:
                        time.sleep(lag)
                    self.ex.submit(self._one, headers)

            def halt(self) -> None:
                self.ex.shutdown(wait=True)
                self.pool.close()

        def fleet_phase(robust: bool) -> dict:
            # Soak defaults are ON now: a bare start_fleet hedges and
            # ejects out of the box, so the baseline leg must opt out
            # explicitly (hedge=None / ejection=None) to stay a
            # baseline — the same knob an operator uses.
            kw: dict = dict(hedge=None, ejection=None)
            if robust:
                kw = dict(
                    hedge=fleet.HedgePolicy(
                        budget_frac=0.05, budget_burst=5.0, min_samples=12),
                    ejection=fleet.EjectionPolicy(
                        min_samples=6, factor=3.0, floor_ms=float(work_ms) * 2,
                        probe_interval_s=0.2, readmit_probes=3),
                )
            hedges0 = {
                o: REGISTRY.counter(
                    "hops_tpu_fleet_hedges_total", labels=("model", "outcome")
                ).value(model="tailbench", outcome=o)
                for o in ("won", "lost", "denied")
            }
            ejections0 = REGISTRY.counter(
                "hops_tpu_fleet_ejections_total", labels=("model",)
            ).value(model="tailbench")
            with fleet.start_fleet("tailbench", replicas, inprocess=True,
                                   scrape_interval_s=0.05, **kw) as f:
                load = _OpenLoop(f.router.endpoint)
                # Warmup seeds every replica's latency window (the
                # adaptive hedge timer refuses to fire from no data).
                load.run(rate_rps, warmup_s)
                time.sleep(0.3)
                with load.lock:
                    load.lat_ms.clear()
                    warm_errors = load.errors
                # The gray replica appears NOW, mid-traffic: slow, not
                # dead — every response still a 200.
                slow_port = f.manager.ready()[-1].port
                faultinject.arm(
                    f"serving.handle=latency:{slow_ms / 1e3}@key={slow_port}")
                load.run(rate_rps, seconds)
                time.sleep(max(1.5, 2.5 * slow_ms / 1e3))  # drain stragglers
                faultinject.disarm()
                load.halt()
                requests = len(load.lat_ms)
                hedges = {
                    o: REGISTRY.counter(
                        "hops_tpu_fleet_hedges_total",
                        labels=("model", "outcome")
                    ).value(model="tailbench", outcome=o) - hedges0[o]
                    for o in ("won", "lost", "denied")
                }
                return {
                    "requests": requests,
                    "p50_ms": pctl(load.lat_ms, 50),
                    "p99_ms": pctl(load.lat_ms, 99),
                    "p999_ms": pctl(load.lat_ms, 99.9),
                    "errors": load.errors - warm_errors,
                    "sheds": load.sheds,
                    "hedges_fired": int(hedges["won"] + hedges["lost"]),
                    "hedges_denied": int(hedges["denied"]),
                    "hedge_rate": round(
                        (hedges["won"] + hedges["lost"]) / max(requests, 1),
                        4),
                    "ejections": int(REGISTRY.counter(
                        "hops_tpu_fleet_ejections_total", labels=("model",)
                    ).value(model="tailbench") - ejections0),
                }

        bare = fleet_phase(robust=False)
        robust = fleet_phase(robust=True)

        # -- phase 3: QoS classes + brownout under overload -------------------
        qos_shed = REGISTRY.counter(
            "hops_tpu_fleet_qos_shed_total",
            labels=("model", "priority", "reason"))
        qshed0 = {
            (p, r): qos_shed.value(model="tailqos", priority=p, reason=r)
            for p in ("interactive", "batch") for r in ("rate", "brownout")
        }
        brownout_gauge = REGISTRY.gauge(
            "hops_tpu_fleet_brownout_level", labels=("model",))
        with fleet.start_fleet(
            "tailqos", 2, inprocess=True,
            scrape_interval_s=0.05,
            hedge=fleet.HedgePolicy(min_samples=12),
            brownout={"slo_p99_ms": 5.0 * qos_work_ms,
                      "burn_window_s": 0.3, "recover_window_s": 1.0},
            # The bucket alone cannot absorb the flood: what passes
            # it still exceeds capacity, so the SLO burns and the
            # brownout ladder has to finish the job.
            class_limits={"batch": {
                "rate_rps": qos_batch_rate * 0.75,
                "burst": qos_batch_rate / 4.0}},
        ) as f:
            inter = _OpenLoop(f.router.endpoint, workers=32)
            batch = _OpenLoop(f.router.endpoint, workers=96)
            threads = [
                threading.Thread(target=inter.run, args=(
                    qos_interactive_rate, seconds,
                    {"X-Priority": "interactive"})),
                threading.Thread(target=batch.run, args=(
                    qos_batch_rate, seconds, {"X-Priority": "batch"})),
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            time.sleep(0.5)
            peak_brownout = int(brownout_gauge.value(model="tailqos"))
            inter.halt()
            batch.halt()
            qshed = {
                f"{p}_{r}": int(qos_shed.value(
                    model="tailqos", priority=p, reason=r) - qshed0[(p, r)])
                for p in ("interactive", "batch")
                for r in ("rate", "brownout")
            }
        qos_result = {
            "interactive": {
                "requests": len(inter.lat_ms),
                "p50_ms": pctl(inter.lat_ms, 50),
                "p99_ms": pctl(inter.lat_ms, 99),
                "sheds": inter.sheds,
                "errors": inter.errors,
            },
            "batch": {
                "requests": len(batch.lat_ms),
                "p50_ms": pctl(batch.lat_ms, 50),
                "p99_ms": pctl(batch.lat_ms, 99),
                "sheds": batch.sheds,
                "errors": batch.errors,
            },
            "router_sheds": qshed,
            "brownout_level_seen": peak_brownout,
        }

        return {
            "work_ms": work_ms,
            "slow_ms": slow_ms,
            "rate_rps": rate_rps,
            "qos_work_ms": qos_work_ms,
            "store": {
                "sequential_p50_ms": pctl(seq_lats, 50),
                # The MEAN is the honest fan-out stat: the gray
                # shard is intermittent (p=0.4), so ~16% of calls
                # stall BOTH the first attempt and its hedge — that
                # remainder is the fault's own floor, and it keeps the
                # p99 pinned at the injected latency in both modes;
                # the hedge removes the single-stall majority, which
                # the mean (and p90) see.
                "sequential_mean_ms": round(float(np.mean(seq_lats)), 2),
                "sequential_p90_ms": pctl(seq_lats, 90),
                "sequential_p99_ms": pctl(seq_lats, 99),
                "fanout_mean_ms": round(float(np.mean(fan_lats)), 2),
                "fanout_p50_ms": pctl(fan_lats, 50),
                "fanout_p90_ms": pctl(fan_lats, 90),
                "fanout_p99_ms": pctl(fan_lats, 99),
                "shard_hedges": int(store_hedges),
            },
            "unhedged": bare,
            "hedged": robust,
            "p99_improvement": round(
                bare["p99_ms"] / max(robust["p99_ms"], 1e-6), 2),
            "qos": qos_result,
        }
    finally:
        faultinject.disarm()
        shutil.rmtree(tmp, ignore_errors=True)


def run_continuous_loop_bench(
    smoke: bool = False,
    *,
    records: int = 2_000,
    publish_rps: float = 600.0,
    min_records: int = 16,
    eval_every: int = 10,
    clients: int = 4,
    work_ms: float = 2.0,
) -> dict:
    """The ``--continuous-loop`` tier: the whole closed loop under load.

    Host-only (JAX pinned to CPU — the checkpoint layer initializes a
    backend; no relay lock). One process runs all four layers at once:

    1. a **producer thread** publishes ``records`` training rows onto a
       pubsub topic at ``publish_rps``;
    2. the **continuous trainer** (``pipeline.run_continuous``) tails
       the topic through a ``StreamingSource`` + ``SpanStream``,
       training a linear model under the exactly-once span ledger with
       an eval gate every ``eval_every`` steps — ONE transient
       ``pubsub.poll`` fault is armed so a supervisor recovery is part
       of the measured run, and ONE mid-run gate is poisoned (the eval
       returns a regressed metric) to force an automatic rollback: that
       candidate must never reach the fleet;
    3. passing candidates are pushed to the model registry and rolled
       into an **in-process serving fleet** (breaker-judged canary +
       capacity-neutral shift);
    4. closed-loop **clients** hammer the router throughout; the
       cutover blip is the longest gap between consecutive successful
       completions while any rollout ran, and the tier asserts zero
       client-visible errors in its JSON.

    Smoke: short topic, 2 full eval gates, the forced rollback, same
    code path end to end.
    """
    import shutil
    import tempfile
    import threading

    from hops_tpu.featurestore.loader import StreamingSource
    from hops_tpu.messaging import pubsub
    from hops_tpu.modelrepo import fleet, registry, serving
    from hops_tpu.pipeline import continuous as cont
    from hops_tpu.runtime import config as rtconfig
    from hops_tpu.runtime import faultinject
    from hops_tpu.runtime.preemption import PreemptionGuard
    from hops_tpu.runtime.resilience import RetryPolicy

    if smoke:
        records, publish_rps = 240, 40.0
        min_records, eval_every = 8, 5
        clients, work_ms = 2, 1.0
    steps_total = records // min_records

    tmp = Path(tempfile.mkdtemp(prefix="hops_tpu_contbench_"))
    rtconfig.configure(workspace=str(tmp / "ws"), project="bench")
    try:
        topic = "contbench-train"
        pubsub.create_topic(topic)

        # -- model artifact: the served predictor bakes in the trained
        # weights, so every published version is distinguishable.
        def export_version(state, step, metric):
            art = tmp / f"art_{step}"
            art.mkdir()
            w = [float(v) for v in state["w"]]
            (art / "p.py").write_text(
                "import threading, time\n"
                f"_W = {w!r}\n"
                "class Predict:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n"
                "    def predict(self, instances):\n"
                "        with self._lock:\n"
                f"            time.sleep({work_ms / 1e3})\n"
                "        return [[sum(wi * xi for wi, xi in zip(_W, v)),\n"
                f"                 {step}] for v in instances]\n"
            )
            return registry.export(art, "contbench",
                                   metrics={"eval": metric, "step": step})

        # v1 (untrained) so the fleet has something to serve from t=0.
        meta0 = export_version({"w": np.zeros(4)}, 0, 0.0)
        serving.create_or_update("contbench", model_name="contbench",
                                 model_version=meta0["version"],
                                 model_server="PYTHON")

        # -- producer ---------------------------------------------------------
        def produce():
            prod = pubsub.Producer(topic)
            rs = np.random.RandomState(0)
            t0 = time.perf_counter()
            for i in range(records):
                target = t0 + (i + 1) / publish_rps
                lag = target - time.perf_counter()
                if lag > 0:
                    time.sleep(lag)
                prod.send({"x": [float(v) for v in rs.rand(4)], "seq": i})

        producer = threading.Thread(target=produce, daemon=True)

        # -- trainer + gate ---------------------------------------------------
        def train_step(state, batch):
            return ({"w": state["w"] + batch["x"].sum(axis=0),
                     "n": np.asarray(state["n"] + len(batch["seq"]))},
                    {"rows": float(len(batch["seq"]))})

        gate_calls = []
        freshness_samples: list[float] = []

        errors = [0]
        done_log: list[float] = []
        done_lock = threading.Lock()
        stop_load = threading.Event()

        def client(f):
            while not stop_load.is_set():
                try:
                    f.predict([[1.0, 1.0, 1.0, 1.0]], timeout_s=30.0)
                    with done_lock:
                        done_log.append(time.perf_counter())
                except Exception:  # noqa: BLE001 — counted, asserted zero
                    # Under done_lock: += on a shared cell is a racy
                    # read-modify-write, and an undercounted error
                    # would fake the tier's zero-errors claim.
                    with done_lock:
                        errors[0] += 1

        faultinject.arm(
            f"pubsub.poll=error:OSError@times=1,after={min_records * 2}")
        rollout_windows: list[tuple[float, float]] = []

        with fleet.start_fleet("contbench", 2, inprocess=True,
                               scrape_interval_s=0.05) as f:
            threads = [threading.Thread(target=client, args=(f,), daemon=True)
                       for _ in range(clients)]
            for t in threads:
                t.start()
            producer.start()

            class _TimedFleet:
                """Fleet facade recording each rollout's wall window so
                the blip is measured only where a blip could occur."""

                def roll_out(self, version, **kw):
                    t0 = time.perf_counter()
                    try:
                        return f.roll_out(version, canary_requests=2,
                                          canary_window_s=5.0, **kw)
                    finally:
                        rollout_windows.append((t0, time.perf_counter()))

            publisher = cont.RegistryFleetPublisher(
                "contbench", export_version, fleet=_TimedFleet())
            src = StreamingSource(topic, group="contbench-trainer",
                                  from_beginning=True, name="contbench")

            def eval_fn(state):
                # Sampled at the gate = right after a segment drained:
                # the steady-state freshness of what training has seen.
                freshness_samples.append(src.watermark_lag_s())
                gate_calls.append(1)
                if len(gate_calls) == 2:  # the poisoned candidate
                    return -1.0
                return float(state["n"])  # monotone: honest gates pass

            stream = cont.SpanStream(
                src, tmp / "ck", collate=cont.collate_column_batch(
                    ["x", "seq"]),
                min_records=min_records, max_records=min_records,
                eval_every=eval_every, stop_on_idle=True, idle_grace_s=1.0)
            t_train0 = time.perf_counter()
            res = cont.run_continuous(
                train_step, {"w": np.zeros(4), "n": np.asarray(0)}, stream,
                directory=str(tmp / "ck"), eval_fn=eval_fn,
                save_every=max(2, eval_every // 2),
                max_recoveries=3,
                recovery_policy=RetryPolicy(base_delay_s=0.01, seed=0),
                publisher=publisher, guard=PreemptionGuard(install=False))
            train_s = time.perf_counter() - t_train0
            faultinject.disarm()
            freshness_lag_s = float(np.median(freshness_samples)) \
                if freshness_samples else 0.0
            # The worst gate sample is where the old inline cutover
            # showed up: training paused ~2 s per passed gate, so the
            # NEXT gate saw the backlog. Async cutover erases the dip —
            # max should sit near the median now.
            freshness_lag_max_s = float(np.max(freshness_samples)) \
                if freshness_samples else 0.0
            time.sleep(0.2)
            stop_load.set()
            for t in threads:
                t.join(timeout=10)
        producer.join(timeout=10)

        blip_ms = 0.0
        with done_lock:
            done_sorted = sorted(done_log)
        for t0, t1 in rollout_windows:
            window = [t for t in done_sorted if t0 - 0.5 <= t <= t1 + 0.5]
            for a, b in zip(window, window[1:]):
                blip_ms = max(blip_ms, (b - a) * 1e3)
        gate_latency_ms = (
            float(np.mean([g["latency_s"] for g in res.gates])) * 1e3
            if res.gates else 0.0)
        failed_gates = [g for g in res.gates if g["outcome"] == "fail"]
        return {
            "spans_per_sec": round(res.ledger["entries"] / train_s, 2),
            "records_per_sec": round(res.ledger["records"] / train_s, 1),
            "steps": res.steps,
            "steps_expected": steps_total,
            "records_trained": res.ledger["records"],
            "records_published": records,
            "ledger_entries": res.ledger["entries"],
            "ledger_contiguous": bool(
                res.ledger["contiguous"] and res.ledger["disjoint"]),
            "freshness_lag_s": round(freshness_lag_s, 3),
            "freshness_lag_max_s": round(freshness_lag_max_s, 3),
            "eval_gates": len(res.gates),
            "eval_gate_rollbacks": len(failed_gates),
            "eval_gate_latency_ms": round(gate_latency_ms, 3),
            "cutovers_completed": sum(
                1 for c in res.cutovers if c["outcome"] == "completed"),
            "cutover_blip_ms": round(blip_ms, 1),
            "recoveries": res.recoveries,
            "client_requests": len(done_sorted),
            "client_errors": int(errors[0]),
        }
    finally:
        faultinject.disarm()
        shutil.rmtree(tmp, ignore_errors=True)


def run_hot_path_bench(smoke: bool = False) -> dict:
    """The ``--hot-path`` micro tier: per-operation costs of the
    serving hot-path layers, measured as tight loops in the
    ``--tracing-overhead`` style (host-only, no accelerator,
    test-enforced bounds in
    tests/test_fleet.py::TestHotPathOverheadBounds).

    - **router relay**: ns/request of the old parse→re-serialize body
      handling vs the zero-copy byte relay (the eliminated work IS the
      measurement — the transport around it is unchanged);
    - **online-store lookup**: ns/key of batched multi-gets on the
      sqlite backend vs the native log-structured engine (skipped when
      the native library isn't built);
    - **KV quant/dequant**: ns/block to quantize + dequantize one
      (page, head_dim) cache block — the at-rest int8 pool's write/read
      tax (jitted on the CPU backend explicitly: this tier is host-only
      and must not initialize an accelerator client without the relay
      lock);
    - **batch assembly**: pooled-buffer reuse hit rate over a steady
      run of same-shape waves;
    - **transport**: per-hop-pair cost of the stdlib
      thread-per-connection ``ThreadingHTTPServer`` (the old transport
      under every server site, and the sanctioned baseline
      instantiation the adhoc-http-server lint rule carves out for this
      file) vs the shared selector event-loop core
      (``hops_tpu.runtime.httpserver``), driven by the same raw-socket
      client so only the server core differs. Two fleet-shaped loads:
      a pipelined keep-alive burst (the router's coalesced
      ``/metrics.json`` scrape shape — the bounded headline) and a
      fresh-dial hop pair (what every pool miss and health probe pays).
    """
    import os
    import shutil
    import tempfile

    iters = 2_000 if smoke else 20_000

    # -- 1. router relay: parse+dump vs byte passthrough -------------------
    body = json.dumps(
        {"instances": [[float(i) / 7.0] * 8 for i in range(32)]}
    ).encode()

    t0 = time.perf_counter()
    for _ in range(iters):
        obj = json.loads(body)
        _ = json.dumps(obj).encode()
    roundtrip_s = time.perf_counter() - t0
    sink = None
    t0 = time.perf_counter()
    for _ in range(iters):
        sink = body  # the zero-copy relay: the bytes ARE the payload
    passthrough_s = time.perf_counter() - t0
    del sink

    # -- 2. online-store lookup: sqlite vs native ---------------------------
    import pandas as pd

    from hops_tpu.featurestore import online
    from hops_tpu.native import kvstore as native_kv

    rows = 400 if smoke else 2_000
    batch = 64
    lookups = 20 if smoke else 100
    tmp = Path(tempfile.mkdtemp(prefix="hops_tpu_hotpath_"))
    df = pd.DataFrame({
        "id": np.arange(rows),
        "v": np.random.RandomState(0).randn(rows),
    })
    rs = np.random.RandomState(1)
    keys = [[int(k)] for k in rs.randint(0, rows, (lookups * batch,))]

    def time_backend(force: str) -> float:
        prev = os.environ.get("HOPS_TPU_ONLINE_BACKEND")
        os.environ["HOPS_TPU_ONLINE_BACKEND"] = force
        try:
            store = online.OnlineStore(tmp / f"hot_{force}")
            store.put_dataframe(df, ["id"])
            store.get_many(keys[:batch])  # warm
            # Min of 3 passes: the per-key window is tens of ms on the
            # smoke tier and a scheduler hiccup inside ONE pass would
            # otherwise swamp the backend difference the bound guards.
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                for i in range(lookups):
                    store.get_many(keys[i * batch:(i + 1) * batch])
                best = min(best, time.perf_counter() - t0)
            store.close()
            return best / (lookups * batch) * 1e9
        finally:
            if prev is None:
                os.environ.pop("HOPS_TPU_ONLINE_BACKEND", None)
            else:
                os.environ["HOPS_TPU_ONLINE_BACKEND"] = prev

    sqlite_ns = time_backend("sqlite")
    native_ns = time_backend("native") if native_kv.available() else None

    # -- 2b. multi-get row decode: per-key json.loads vs one batched
    # array parse (the remaining Python-side per-key cost after the
    # native backend took the lookup itself to ~10us) ----------------------
    raw_rows = [
        json.dumps({"id": int(i), "v": float(i) / 3.0, "name": f"row-{i}"})
        for i in range(64)
    ]
    decode_reps = max(1, iters // 40)
    t0 = time.perf_counter()
    for _ in range(decode_reps):
        _ = [json.loads(r) for r in raw_rows]
    per_key_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(decode_reps):
        _ = online._decode_rows(raw_rows)
    batched_s = time.perf_counter() - t0
    decode_keys = decode_reps * len(raw_rows)

    # -- 3. KV quantize/dequantize per cache block --------------------------
    from hops_tpu.ops.attention import dequantize_kv, quantize_kv

    page, head_dim, blocks = 16, 64, 64
    x = jnp.asarray(
        np.random.RandomState(2).randn(blocks, page, head_dim), jnp.float32
    )
    qfn = jax.jit(lambda a: quantize_kv(a), backend="cpu")
    dfn = jax.jit(lambda q, s: dequantize_kv(q, s), backend="cpu")
    qv, sc = jax.block_until_ready(qfn(x))
    jax.block_until_ready(dfn(qv, sc))
    reps = 20 if smoke else 200
    t0 = time.perf_counter()
    for _ in range(reps):
        qv, sc = qfn(x)
    jax.block_until_ready((qv, sc))
    quant_ns_block = (time.perf_counter() - t0) / (reps * blocks) * 1e9
    t0 = time.perf_counter()
    for _ in range(reps):
        back = dfn(qv, sc)
    jax.block_until_ready(back)
    dequant_ns_block = (time.perf_counter() - t0) / (reps * blocks) * 1e9

    # -- 4. batch-assembly reuse ------------------------------------------
    from hops_tpu.modelrepo.batch import AssemblyPool

    pool = AssemblyPool(depth=4)
    waves = 200 if smoke else 1_000
    for _ in range(waves):
        buf = pool.take((64, 8), np.float32, site="bench")
        buf[:1] = 1.0
        pool.give(buf)
    hit_rate = pool.hit_rate()

    # -- 5. transport: stdlib thread-per-connection vs event loop ----------
    import socket
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from hops_tpu.runtime.httpserver import HTTPServer as _EventLoopServer

    t_payload = b'{"predictions": [[1.0, 2.0, 3.0, 4.0]]}'

    class _StdlibEcho(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # Without this the stdlib numbers drown in Nagle/delayed-ACK
        # stalls (>10 ms/request) — the bound must measure the
        # thread-per-connection core, not a socket-option artifact.
        disable_nagle_algorithm = True

        def do_GET(self):
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(t_payload)))
            self.end_headers()
            self.wfile.write(t_payload)

        def log_message(self, *a):
            pass

    class _StdlibSrv(ThreadingHTTPServer):
        # Match the event-loop core's listen backlog: the stdlib
        # default (5) drops SYNs under fan-in and the retransmit stalls
        # would charge a kernel-queue artifact to the server core.
        request_queue_size = 128
        daemon_threads = True

    _wire = b"GET /echo HTTP/1.1\r\nHost: bench\r\n\r\n"

    def _read_responses(s: socket.socket, n: int, buf: list) -> None:
        # Content-Length framing over a shared carry buffer: pipelined
        # responses arrive back-to-back in one recv.
        data = buf[0]
        for _ in range(n):
            while b"\r\n\r\n" not in data:
                chunk = s.recv(65536)
                if not chunk:
                    raise OSError("server closed mid-response")
                data += chunk
            head, _, rest = data.partition(b"\r\n\r\n")
            length = 0
            for hline in head.split(b"\r\n")[1:]:
                k, _, v = hline.partition(b":")
                if k.strip().lower() == b"content-length":
                    length = int(v.strip())
            while len(rest) < length:
                chunk = s.recv(65536)
                if not chunk:
                    raise OSError("server closed mid-body")
                rest += chunk
            data = rest[length:]
        buf[0] = data

    def _pipelined_pass_us(port: int, bursts: int, depth: int) -> float:
        # The scrape shape: one pooled keep-alive connection, `depth`
        # GETs written in a single sendall (HTTPPool.pipeline's wire
        # pattern), responses read back in order.
        s = socket.create_connection(("127.0.0.1", port), timeout=20)
        try:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            buf = [b""]
            s.sendall(_wire)
            _read_responses(s, 1, buf)  # warm (stdlib: thread spawn)
            t0 = time.perf_counter()
            for _ in range(bursts):
                s.sendall(_wire * depth)
                _read_responses(s, depth, buf)
            return (time.perf_counter() - t0) / (bursts * depth) * 1e6
        finally:
            s.close()

    def _dial_pass_us(port: int, hops: int) -> float:
        # The pool-miss / health-probe shape: dial, one request, close.
        # Under thread-per-connection every such hop pays a thread
        # spawn + handler setup; the event loop pays one accept.
        t0 = time.perf_counter()
        for _ in range(hops):
            s = socket.create_connection(("127.0.0.1", port), timeout=20)
            try:
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                s.sendall(_wire)
                _read_responses(s, 1, [b""])
            finally:
                s.close()
        return (time.perf_counter() - t0) / hops * 1e6

    t_bursts = 10 if smoke else 40
    t_depth = 64
    t_hops = 60 if smoke else 200

    def _echo_route(method, path, headers, req_body):
        return 200, {"Content-Type": "application/json"}, t_payload

    stdlib_srv = _StdlibSrv(("127.0.0.1", 0), _StdlibEcho)
    stdlib_thread = threading.Thread(target=stdlib_srv.serve_forever, daemon=True)
    stdlib_thread.start()
    ev_srv = _EventLoopServer(_echo_route, name="bench-transport", workers=8)
    try:
        std_port = stdlib_srv.server_address[1]
        # Both servers alive, passes interleaved min-of-5: an ambient
        # load spike lands on BOTH sides of the ratio instead of
        # silently inflating whichever server happened to be measured
        # during it (the min over interleaved passes is the honest
        # steady-state on a shared box).
        transport_stdlib_us = transport_eventloop_us = float("inf")
        transport_dial_stdlib_us = transport_dial_eventloop_us = float("inf")
        for _ in range(5):
            transport_stdlib_us = min(
                transport_stdlib_us,
                _pipelined_pass_us(std_port, t_bursts, t_depth))
            transport_eventloop_us = min(
                transport_eventloop_us,
                _pipelined_pass_us(ev_srv.port, t_bursts, t_depth))
            transport_dial_stdlib_us = min(
                transport_dial_stdlib_us, _dial_pass_us(std_port, t_hops))
            transport_dial_eventloop_us = min(
                transport_dial_eventloop_us, _dial_pass_us(ev_srv.port, t_hops))
    finally:
        stdlib_srv.shutdown()
        stdlib_srv.server_close()
        stdlib_thread.join(10)
        ev_srv.stop()

    # -- 6. wire codec: packed columnar vs JSON on the predict body --------
    # Decode produces the instance TENSOR on both legs (json.loads
    # alone hands back nested lists the batcher would still have to
    # np.asarray — pricing bytes→tensor is the honest comparison);
    # encode starts from the ndarray, so the JSON leg pays the
    # tolist() float loop the packed frame eliminates by design.
    from hops_tpu.runtime import wirecodec

    codec_arr = np.asarray(
        [[float(i) / 7.0] * 8 for i in range(32)], dtype=np.float32)
    codec_json_body = json.dumps({"instances": codec_arr.tolist()}).encode()
    codec_frame = wirecodec.encode_instances(codec_arr)
    codec_reps = max(1, iters // 4)

    t0 = time.perf_counter()
    for _ in range(codec_reps):
        _ = json.dumps({"instances": codec_arr.tolist()}).encode()
    codec_json_enc_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(codec_reps):
        _ = wirecodec.encode_instances(codec_arr)
    codec_packed_enc_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(codec_reps):
        _ = np.asarray(json.loads(codec_json_body)["instances"],
                       dtype=np.float32)
    codec_json_dec_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(codec_reps):
        _ = wirecodec.decode_instances(codec_frame)
    codec_packed_dec_s = time.perf_counter() - t0

    # The 32-key row batch (the shard get_many response shape; typed
    # numeric columns — string features would ride a JSON-bytes column
    # and land near parity).
    codec_rows = [{"user_id": i, "score": float(i) / 4.0, "clicks": i * 3}
                  for i in range(32)]
    codec_rows_json = json.dumps({"rows": codec_rows}).encode()
    codec_rows_frame = wirecodec.encode_rows(codec_rows)
    t0 = time.perf_counter()
    for _ in range(codec_reps):
        _ = json.loads(codec_rows_json)["rows"]
    rows_json_dec_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(codec_reps):
        _ = wirecodec.decode_rows(codec_rows_frame)
    rows_packed_dec_s = time.perf_counter() - t0

    # -- 6b. shard multi_get: local vs remote-JSON vs remote-packed --------
    # Same rows behind three paths: in-process shard files, a shardd
    # server pinned JSON-only, and a packed-negotiating shardd — the
    # per-key price of each wire. µs/key of 32-key batches, min of 3.
    from hops_tpu.featurestore.online_serving import ShardedOnlineStore
    from hops_tpu.jobs.placement import shardd

    sh_rows = 256 if smoke else 1024
    sh_batches = 10 if smoke else 40
    sh_tmp = Path(tempfile.mkdtemp(prefix="hops_tpu_shardbench_"))
    sdf = pd.DataFrame({
        "user_id": np.arange(sh_rows),
        "score": np.random.RandomState(3).randn(sh_rows),
        "clicks": np.arange(sh_rows) * 3,
    })
    sh_keys = [
        [{"user_id": int(k)}
         for k in np.random.RandomState(4 + b).randint(0, sh_rows, 32)]
        for b in range(sh_batches)
    ]

    def _multiget_us_per_key(store) -> float:
        store.multi_get(sh_keys[0])  # warm (handshake + breaker state)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for batch_keys in sh_keys:
                store.multi_get(batch_keys)
            best = min(best, time.perf_counter() - t0)
        return best / (sh_batches * 32) * 1e6

    local_store = ShardedOnlineStore(
        "bench_users", primary_key=["user_id"], shards=1,
        root=sh_tmp / "local")
    local_store.put_dataframe(sdf)
    servers, remote_stores = [], {}
    try:
        for tag, codecs in (("json", ["json"]), ("packed", None)):
            cfg = {"store": "bench_users", "shard_index": 0, "shards": 1,
                   "primary_key": ["user_id"],
                   "root": str(sh_tmp / f"srv_{tag}"), "port": 0}
            if codecs is not None:
                cfg["codecs"] = codecs
            srv = shardd.ShardServer(cfg)
            servers.append(srv)
            srv._put_rows(sdf.to_dict("records"))
            remote_stores[tag] = ShardedOnlineStore(
                "bench_users", primary_key=["user_id"],
                endpoints=[f"http://127.0.0.1:{srv.port}"])
        shard_local_us = _multiget_us_per_key(local_store)
        shard_json_us = _multiget_us_per_key(remote_stores["json"])
        shard_packed_us = _multiget_us_per_key(remote_stores["packed"])
    finally:
        for srv in servers:
            srv.stop()
        local_store.close()
        shutil.rmtree(sh_tmp, ignore_errors=True)

    shutil.rmtree(tmp, ignore_errors=True)
    out = {
        "relay_json_roundtrip_ns_per_request": round(
            roundtrip_s / iters * 1e9, 1),
        "relay_zero_copy_ns_per_request": round(
            passthrough_s / iters * 1e9, 1),
        "relay_saved_ns_per_request": round(
            max(0.0, roundtrip_s - passthrough_s) / iters * 1e9, 1),
        "online_lookup_sqlite_ns": round(sqlite_ns, 1),
        "online_lookup_native_ns": (
            round(native_ns, 1) if native_ns is not None else None),
        "online_native_speedup": (
            round(sqlite_ns / native_ns, 2) if native_ns else None),
        "online_row_decode_per_key_ns": round(
            per_key_s / decode_keys * 1e9, 1),
        "online_row_decode_batched_ns": round(
            batched_s / decode_keys * 1e9, 1),
        "online_row_decode_speedup": round(
            per_key_s / max(batched_s, 1e-12), 2),
        "kv_quant_ns_per_block": round(quant_ns_block, 1),
        "kv_dequant_ns_per_block": round(dequant_ns_block, 1),
        "assembly_reuse_hit_rate": round(hit_rate, 4),
        "transport_stdlib_us_per_request": round(transport_stdlib_us, 2),
        "transport_eventloop_us_per_request": round(
            transport_eventloop_us, 2),
        "transport_speedup": round(
            transport_stdlib_us / max(transport_eventloop_us, 1e-9), 2),
        "transport_dial_stdlib_us": round(transport_dial_stdlib_us, 2),
        "transport_dial_eventloop_us": round(transport_dial_eventloop_us, 2),
        "transport_dial_speedup": round(
            transport_dial_stdlib_us / max(transport_dial_eventloop_us, 1e-9),
            2),
        "codec_predict_json_encode_ns": round(
            codec_json_enc_s / codec_reps * 1e9, 1),
        "codec_predict_packed_encode_ns": round(
            codec_packed_enc_s / codec_reps * 1e9, 1),
        "codec_predict_encode_speedup": round(
            codec_json_enc_s / max(codec_packed_enc_s, 1e-12), 2),
        "codec_predict_json_decode_ns": round(
            codec_json_dec_s / codec_reps * 1e9, 1),
        "codec_predict_packed_decode_ns": round(
            codec_packed_dec_s / codec_reps * 1e9, 1),
        "codec_predict_decode_speedup": round(
            codec_json_dec_s / max(codec_packed_dec_s, 1e-12), 2),
        "codec_rows_json_decode_ns": round(
            rows_json_dec_s / codec_reps * 1e9, 1),
        "codec_rows_packed_decode_ns": round(
            rows_packed_dec_s / codec_reps * 1e9, 1),
        "codec_rows_decode_speedup": round(
            rows_json_dec_s / max(rows_packed_dec_s, 1e-12), 2),
        "shard_multiget_local_us_per_key": round(shard_local_us, 2),
        "shard_multiget_remote_json_us_per_key": round(shard_json_us, 2),
        "shard_multiget_remote_packed_us_per_key": round(
            shard_packed_us, 2),
    }
    return out


def run_fault_overhead_bench(calls: int = 1_000_000) -> dict:
    """Disarmed fault-injection overhead: the zero-cost claim, measured.

    Every hot path in the stack (loader batch production, serving
    handlers, checkpoint saves) carries a ``faultinject.fire(point)``
    call. The contract is that a DISARMED registry costs one attribute
    load + ``is None`` test — this smoke times a tight loop of disarmed
    fires against an empty same-shape loop and reports ns/call, so a
    regression (someone adds work before the arm check) shows up as a
    number, not a vibe. Host-only: no accelerator, no relay."""
    from hops_tpu.runtime import faultinject

    if faultinject.armed():
        raise RuntimeError("disarm HOPS_TPU_FAULTS before the overhead bench")
    fire = faultinject.fire

    def loop_fire(n: int) -> None:
        for _ in range(n):
            fire("loader.read")

    def loop_empty(n: int) -> None:
        for _ in range(n):
            pass

    loop_fire(10_000)  # warm caches / specialize
    loop_empty(10_000)
    t0 = time.perf_counter()
    loop_fire(calls)
    fire_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    loop_empty(calls)
    empty_s = time.perf_counter() - t0
    ns_per_call = max(0.0, (fire_s - empty_s) / calls * 1e9)
    return {
        "calls": calls,
        "ns_per_disarmed_fire": round(ns_per_call, 1),
        "fire_loop_s": round(fire_s, 4),
        "empty_loop_s": round(empty_s, 4),
    }


def run_tracing_overhead_bench(calls: int = 200_000) -> dict:
    """Tracing-plumbing overhead on the serving hot path, measured.

    Every request handler now calls into ``telemetry/tracing.py``
    (``start_trace`` / ``child_span`` / ``current_trace_id``); the
    contract mirrors faultinject's: with tracing DISABLED each entry
    point is one module-flag test, and with tracing on but the request
    untraced, one extra contextvar read. This smoke times tight loops
    of the three hot-path shapes against an empty same-shape loop:

    - ``disabled``: ``child_span`` + ``current_trace_id`` with tracing
      off — the cost every request pays when an operator disables
      tracing (test-bounded, like the disarmed-fire bound);
    - ``untraced``: the same with tracing ON but no active trace — the
      cost of instrumented-but-unsampled paths;
    - ``sampled``: a full ``start_trace`` + entered ``child_span`` per
      iteration — the per-request cost of a 100%-sampled trace with
      ring recording.

    Host-only: no accelerator, no relay.
    """
    from hops_tpu.telemetry import tracing

    prev_enabled = tracing.enabled()
    prev_rate = tracing.TRACER.sample_rate

    def timed_loop(fn, n):
        fn(5_000)  # warm caches / specialize

        def empty(k):
            for _ in range(k):
                pass

        empty(5_000)
        t0 = time.perf_counter()
        fn(n)
        body_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        empty(n)
        empty_s = time.perf_counter() - t0
        return max(0.0, (body_s - empty_s) / n * 1e9)

    child_span = tracing.child_span
    current_trace_id = tracing.current_trace_id

    def hot_path(n):
        for _ in range(n):
            with child_span("bench.hop"):
                pass
            current_trace_id()

    def sampled(n):
        for _ in range(n):
            with tracing.start_trace("bench.request"):
                with child_span("bench.hop"):
                    pass

    try:
        tracing.configure(enabled=False)
        disabled_ns = timed_loop(hot_path, calls)
        tracing.configure(enabled=True, sample_rate=1.0)
        untraced_ns = timed_loop(hot_path, calls)
        sampled_ns = timed_loop(sampled, max(1, calls // 10))
    finally:
        tracing.configure(enabled=prev_enabled, sample_rate=prev_rate)
    return {
        "calls": calls,
        "ns_per_disabled_span": round(disabled_ns, 1),
        "ns_per_untraced_span": round(untraced_ns, 1),
        "us_per_sampled_trace": round(sampled_ns / 1e3, 3),
    }


def run_capture_overhead_bench(calls: int = 1_000_000) -> dict:
    """Disabled workload-capture overhead: the zero-cost claim, measured.

    Every serving and router request path now guards its capture tap
    with ``workload.capturing()``; the contract (the same one disarmed
    ``faultinject.fire`` and disabled tracing keep) is that with no
    recorder armed the check is ONE module-global read — no record
    dict is ever built. This times tight loops of the two disarmed
    shapes against an empty same-shape loop and reports ns/call, so a
    regression (someone hoists record construction above the guard)
    shows up as a number. Host-only: no accelerator, no relay."""
    from hops_tpu.telemetry import workload

    if workload.capturing():
        raise RuntimeError("stop workload capture before the overhead bench")
    capturing = workload.capturing
    record_request = workload.record_request

    def loop_guard(n: int) -> None:
        # The real call-site shape: guard, then (disarmed) nothing.
        for _ in range(n):
            if capturing():
                record_request(surface="bench", endpoint="bench")

    def loop_record(n: int) -> None:
        # The unguarded entry point: record_request's own disarmed
        # fast path (one global read + return).
        for _ in range(n):
            record_request()

    def loop_empty(n: int) -> None:
        for _ in range(n):
            pass

    loop_guard(10_000)  # warm caches / specialize
    loop_record(10_000)
    loop_empty(10_000)
    t0 = time.perf_counter()
    loop_guard(calls)
    guard_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    loop_record(calls)
    record_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    loop_empty(calls)
    empty_s = time.perf_counter() - t0
    return {
        "calls": calls,
        "ns_per_disabled_check": round(
            max(0.0, (guard_s - empty_s) / calls * 1e9), 1),
        "ns_per_disabled_record": round(
            max(0.0, (record_s - empty_s) / calls * 1e9), 1),
        "guard_loop_s": round(guard_s, 4),
        "empty_loop_s": round(empty_s, 4),
    }


def run_workload_replay_bench(
    artifact: str | None = None,
    scenario: str | None = None,
    speed: float = 1.0,
    seed: int = 0,
    smoke: bool = False,
    replicas: int = 2,
) -> dict:
    """The ``--replay`` tier: re-issue a captured (or synthesized)
    workload artifact open-loop against an in-process serving fleet.

    The artifact IS the experiment: the same captured stream re-runs
    against any configuration at ``--replay-speed`` multiples, and the
    JSON line carries the recorded-vs-replayed comparison (status mix,
    throughput, latency percentiles) plus arrival fidelity — achieved
    vs intended inter-arrival error, the number that says whether the
    replay actually reproduced the arrival process it promised
    (acceptance: p50 error < 10% of the intended gap at 1x speed).

    ``scenario`` (instead of ``artifact``) synthesizes one of the
    catalog scenarios (diurnal | herd | hot_key | tenant_spray) into a
    temp dir first — captured and synthetic workloads replay through
    one code path. Host-only: no accelerator, no relay lock.

    Replayed per-tenant metrics collapse through the router's
    ``limiter.label_for``, so replaying a tenant-spray capture cannot
    mint unbounded metric children in the router's own registry.
    """
    import shutil
    import tempfile

    from hops_tpu.modelrepo import fleet, registry, serving
    from hops_tpu.runtime import config as rtconfig
    from hops_tpu.telemetry import workload

    if artifact is None and scenario is None:
        raise ValueError("replay needs an artifact path or a scenario name")

    tmp = Path(tempfile.mkdtemp(prefix="hops_tpu_replaybench_"))
    rtconfig.configure(workspace=str(tmp / "ws"), project="bench")
    try:
        if artifact is None:
            synth_kw: dict = {}
            if smoke:
                # Shrink every scenario to a ~2s CPU-safe footprint.
                synth_kw = {
                    "diurnal": {"duration_s": 2.0, "base_rps": 8.0},
                    "herd": {"duration_s": 2.0, "base_rps": 6.0,
                             "burst_size": 12, "burst_window_s": 0.1},
                    "hot_key": {"duration_s": 2.0, "base_rps": 10.0,
                                "entities": 64, "batch": 4},
                    "tenant_spray": {"duration_s": 2.0, "base_rps": 20.0},
                }.get(scenario, {})
            artifact = str(workload.synthesize(
                scenario, tmp / "artifact", seed=seed, **synth_kw))
            _note(f"synthesized scenario {scenario!r} into {artifact}")
        loaded = workload.load_artifact(artifact)
        records = loaded["records"]
        # A fleet capture records each request at BOTH the router front
        # door and the replica that served it; replay the front-door
        # stream (what clients actually sent), not the doubled view.
        surfaces = {r.get("surface") for r in records}
        if "router" in surfaces and len(surfaces) > 1:
            records = [r for r in records if r.get("surface") == "router"]
        if smoke and len(records) > 64 and scenario is None:
            records = records[:64]
        if not records:
            raise ValueError(f"artifact {artifact} holds no records")
        _note(f"replaying {len(records)} recorded request(s) at {speed}x")

        if smoke:
            replicas = 1
        art = tmp / "art"
        art.mkdir()
        # Echo predictor: payload-shape agnostic, so captured dense,
        # entity-join, and synthetic bodies all replay against it.
        (art / "p.py").write_text(
            "class Predict:\n"
            "    def predict(self, instances):\n"
            "        return [[1.0] for _ in instances]\n"
        )
        registry.export(art, "replaybench", metrics={"v": 1.0})
        serving.create_or_update(
            "replaybench", model_name="replaybench", model_version=1,
            model_server="PYTHON")
        with fleet.start_fleet("replaybench", replicas, inprocess=True,
                               scrape_interval_s=0.05) as f:
            report = workload.replay(
                records, f.router.endpoint, speed=speed, seed=seed,
                tenant_label=f.router.limiter.label_for,
            )
        meta = loaded["manifest"].get("meta", {})
        out = {
            "artifact": str(artifact),
            "records": len(records),
            "scenario": meta.get("scenario"),
            "replicas": replicas,
            **report,
        }
        return out
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _lm_serving_workload(requests: int, seed: int, rate_rps: float, *,
                         short, long, long_frac, budget):
    """Seeded Poisson arrival process with a mixed prompt-length
    distribution: the open-loop load model serving actually sees
    (bursts + a heavy tail of long prompts), not a closed batch."""
    rs = np.random.RandomState(seed)
    arrivals = np.cumsum(rs.exponential(1.0 / rate_rps, requests))
    prompts, budgets = [], []
    for _ in range(requests):
        lo, hi = long if rs.rand() < long_frac else short
        prompts.append(rs.randint(0, 256, rs.randint(lo, hi + 1)).astype(np.int32))
        budgets.append(int(rs.randint(budget[0], budget[1] + 1)))
    return arrivals, prompts, budgets


def _drive_lm_serving(engine, arrivals, prompts, budgets) -> dict:
    """Open-loop driver: submit each request at its arrival time (wall
    clock), step the engine whenever it has work, and collect per-ticket
    TTFT + tokens. Late arrivals queue — exactly the backpressure the
    paged/chunked scheduler is supposed to absorb."""
    n = len(prompts)
    stats0 = engine.stats()
    t0 = time.perf_counter()
    done: dict[int, list[int]] = {}
    order: list[int] = []
    i = 0
    while len(done) < n:
        now = time.perf_counter() - t0
        while i < n and arrivals[i] <= now:
            order.append(engine.submit(prompts[i], max_new_tokens=budgets[i]))
            i += 1
        if engine.has_work:
            for t in engine.step():
                done[t] = engine.result(t)
        elif i < n:
            time.sleep(min(0.002, max(0.0, arrivals[i] - now)))
    wall = time.perf_counter() - t0
    stats1 = engine.stats()
    ttfts = np.asarray([engine.ttft_s[t] for t in order])
    tokens = sum(len(v) for v in done.values())
    d_disp = stats1["dispatches"] - stats0["dispatches"]
    occ = (
        stats1["mean_occupancy"] * stats1["dispatches"]
        - stats0["mean_occupancy"] * stats0["dispatches"]
    ) / max(d_disp, 1)
    out = {
        "wall_s": wall,
        "tokens": tokens,
        "tokens_per_sec": tokens / wall,
        "ttft_p50_ms": float(np.percentile(ttfts, 50) * 1e3),
        "ttft_p99_ms": float(np.percentile(ttfts, 99) * 1e3),
        "slot_occupancy": round(occ, 4),
    }
    if stats1.get("cache_layout") == "paged":
        out.update(
            block_pool_peak_util=round(
                stats1["blocks_peak_used"] / stats1["blocks_total"], 4
            ),
            prefill_chunks=stats1["prefill_chunks"] - stats0["prefill_chunks"],
            preempted_prefills=stats1["preemptions"] - stats0["preemptions"],
        )
    return out


def run_lm_serving_bench(
    requests: int = 40,
    seed: int = 0,
    rate_rps: float | None = None,
    smoke: bool = False,
    tp: bool = False,
) -> dict:
    """The ``--lm-serving`` tier: the continuous-batching LM engine
    under seeded Poisson load — paged KV cache + chunked prefill vs the
    dense full-prefill baseline AT EQUAL CACHE MEMORY.

    Both engines get the same token budget of persistent KV memory;
    the dense layout spends it on ``budget / max_decode_len`` max-length
    slot reservations, while the paged layout spends it on a block pool
    shared by 2x the slots (slot count bounded by LIVE tokens). Under
    the same arrival process the paged engine keeps more requests
    decoding concurrently and never freezes the batch behind a long
    prompt's prefill — which is what tokens/s and TTFT p99 measure.
    Token streams are bit-identical between the two (the equivalence
    tests pin this), so the comparison is pure scheduling/memory.

    ``tp=True`` runs both engines tensor-parallel over every visible
    device (``parallel/tp_inference`` Megatron sharding, paged pools
    head-sharded) — the multichip variant; tokens/s/chip divides by the
    mesh size.
    """
    import jax  # noqa: F811 — resolved at call time under forced-cpu smoke
    import jax.numpy as jnp

    from hops_tpu.models.transformer import TransformerLM
    from hops_tpu.modelrepo.lm_engine import LMEngine

    if smoke:
        cap, d_model, layers = 96, 32, 2
        page, chunk = 8, 16
        short, long_, long_frac, budget = (4, 12), (32, 64), 0.3, (4, 8)
        requests = min(requests, 10)
        dense_slots = 2
        rate = rate_rps or 6.0
    else:
        cap, d_model, layers = 192, 64, 2
        page, chunk = 16, 32
        short, long_, long_frac, budget = (8, 24), (96, 160), 0.3, (8, 24)
        dense_slots = 4
        # CPU-tier tuned load point: deep enough queueing that the
        # dense engine's 4 slots saturate and its multi-request
        # admission waves pad to the 192 bucket (monolithic prefill
        # stalling decode), while the paged engine's 2x slots + fused
        # prefill chunks keep absorbing arrivals — measured 3-4x
        # tokens/s and ~40x lower TTFT p99 across reps on the CPU
        # tier. TPU runs should pass --lm-serving-rate sized to the
        # chip.
        rate = rate_rps or 40.0
    mesh = None
    n_chips = 1
    if tp:
        from jax.sharding import Mesh

        devs = np.array(jax.devices())
        if devs.size > 1:
            mesh = Mesh(devs, ("model",))
            n_chips = devs.size
    budget_tokens = dense_slots * cap
    paged_slots = dense_slots * 2
    pool_blocks = 1 + budget_tokens // page
    # int8 pool at the SAME byte budget: 1-byte values + one fp32 scale
    # per position for each of k/v, vs 4-byte fp32 values — the block
    # count scales by the per-token byte ratio (~3.2x at head_dim 16).
    head_dim = d_model // 4
    fp_tok_bytes = head_dim * 4 * 2
    q8_tok_bytes = (head_dim + 4) * 2
    pool_blocks_int8 = 1 + (budget_tokens * fp_tok_bytes) // (
        q8_tok_bytes * page)
    live_tokens_ratio = (pool_blocks_int8 - 1) / max(pool_blocks - 1, 1)

    model = TransformerLM(
        vocab_size=256, d_model=d_model, num_heads=4, num_layers=layers,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=cap,
        ragged_decode=True,
    )
    model_int8 = model.clone(kv_cache_dtype="int8")
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    _note(
        f"lm-serving bench: budget {budget_tokens} KV tokens -> dense "
        f"{dense_slots} slots vs paged {paged_slots} slots "
        f"({pool_blocks} blocks of {page}; int8 {pool_blocks_int8} blocks "
        f"= {live_tokens_ratio:.2f}x live tokens), {requests} req @ {rate}/s"
    )

    results = {}
    for layout in ("dense", "paged", "paged_int8"):
        if layout == "dense":
            engine = LMEngine(
                model, params, slots=dense_slots,
                prefill_buckets=(max(32, chunk), cap), mesh=mesh,
            )
        elif layout == "paged_int8":
            engine = LMEngine(
                model_int8, params, slots=paged_slots, kv_page_size=page,
                kv_pool_blocks=int(pool_blocks_int8), prefill_chunk=chunk,
                mesh=mesh,
            )
        else:
            engine = LMEngine(
                model, params, slots=paged_slots, kv_page_size=page,
                kv_pool_blocks=pool_blocks, prefill_chunk=chunk, mesh=mesh,
            )
        # Warm the compiles OUTSIDE the timed window: one short and one
        # long request touch every program shape the workload uses.
        rs = np.random.RandomState(999)
        engine.submit(rs.randint(0, 256, short[1]), max_new_tokens=2)
        engine.submit(rs.randint(0, 256, long_[1]), max_new_tokens=2)
        engine.run()
        _note(f"{layout}: warm, driving Poisson load")
        arrivals, prompts, budgets = _lm_serving_workload(
            requests, seed, rate, short=short, long=long_,
            long_frac=long_frac, budget=budget,
        )
        results[layout] = _drive_lm_serving(engine, arrivals, prompts, budgets)
        _note(
            f"{layout}: {results[layout]['tokens_per_sec']:.1f} tok/s, "
            f"ttft p99 {results[layout]['ttft_p99_ms']:.0f} ms"
        )
    paged, dense = results["paged"], results["dense"]
    q8 = results["paged_int8"]
    return {
        "tokens_per_sec_per_chip": paged["tokens_per_sec"] / n_chips,
        "ttft_p50_ms": round(paged["ttft_p50_ms"], 1),
        "ttft_p99_ms": round(paged["ttft_p99_ms"], 1),
        "slot_occupancy": paged["slot_occupancy"],
        "block_pool_peak_util": paged["block_pool_peak_util"],
        "prefill_chunks": paged["prefill_chunks"],
        "preempted_prefills": paged["preempted_prefills"],
        "dense_tokens_per_sec_per_chip": round(
            dense["tokens_per_sec"] / n_chips, 2
        ),
        "dense_ttft_p99_ms": round(dense["ttft_p99_ms"], 1),
        "speedup_vs_dense": round(
            paged["tokens_per_sec"] / dense["tokens_per_sec"], 3
        ),
        # int8 pool at the SAME byte budget: the capacity headline is
        # live tokens per pool (blocks scale by the per-token byte
        # ratio); greedy streams stay bit-identical (test-pinned), so
        # tokens/s differences are scheduling, not output.
        "int8_tokens_per_sec_per_chip": round(
            q8["tokens_per_sec"] / n_chips, 2
        ),
        "int8_ttft_p99_ms": round(q8["ttft_p99_ms"], 1),
        "int8_pool_blocks": int(pool_blocks_int8),
        "fp_pool_blocks": int(pool_blocks),
        "int8_live_tokens_ratio": round(live_tokens_ratio, 2),
        "int8_block_pool_peak_util": q8["block_pool_peak_util"],
        "requests": requests,
        "rate_rps": rate,
        "n_chips": n_chips,
        "platform": jax.devices()[0].platform,
    }


class _ProbeTimeout(RuntimeError):
    """The health probe hung past its budget (relay likely wedged)."""


class _ProbeError(RuntimeError):
    """The health probe answered, but with an error."""


def probe_with_retry(
    attempt_deadline_s: float = 150.0,
    probe_timeout_s: float = 120,
    total_timeout_s: float = 360.0,
    base_delay_s: float = 15.0,
) -> tuple[dict | None, str, str]:
    """The BENCH_r04/r05 wedge fix: the pre-run health probe under a
    bounded ``RetryPolicy`` with per-attempt ``with_deadline`` instead
    of one open-ended 240 s wait. Returns ``(health, kind, error)`` —
    ``health`` non-None means reachable; otherwise ``kind`` is
    ``probe_timeout`` (hang — the wedge signature) or ``relay_error``
    (probe answered with an error), which flows into the stale line's
    ``stale_kind`` so consumers can tell the two apart. The budgets are
    parameters so the deadline contract is testable at test-sized
    timescales (tests/test_loader.py pins that a hung probe returns
    within ~total_timeout_s instead of wedging the driver)."""
    from hops_tpu.runtime.resilience import DeadlineExceeded, RetryPolicy, with_deadline

    def attempt() -> dict:
        # with_deadline backstops probe_tpu's own subprocess wait: even
        # a hang in process spawning must not blow the attempt budget.
        # (probe_tpu's timeout rides positionally — with_deadline's own
        # second parameter is also named timeout_s.)
        health = with_deadline(
            probe_tpu, attempt_deadline_s, probe_timeout_s, op="bench.probe"
        )
        if health.get("ok"):
            return health
        err = str(health.get("error", "unknown"))
        if "hung" in err:
            raise _ProbeTimeout(err)
        raise _ProbeError(err)

    policy = RetryPolicy(
        max_attempts=2, base_delay_s=base_delay_s, jitter=False,
        total_timeout_s=total_timeout_s,
        retry_on=(_ProbeTimeout, _ProbeError, DeadlineExceeded),
    )
    try:
        return policy.call(attempt, op="bench.probe"), "", ""
    except (DeadlineExceeded, _ProbeTimeout) as e:
        return None, "probe_timeout", str(e)
    except Exception as e:  # noqa: BLE001 — classified for the stale line
        return None, "relay_error", str(e)


def probe_tpu(timeout_s: int = 120) -> dict:
    """Cheaply answer "is the TPU reachable?" without risking a wedge.

    The relay is single-tenant and killed clients can wedge it
    (BENCHMARKS.md operational note), so the probe runs a tiny matmul
    in a SUBPROCESS: on timeout the parent stops waiting but lets the
    child run to completion/exit on its own (never killed mid-
    handshake). This is how a recovered relay is detected so the real
    bench can re-measure — the smoke path stays CPU-pinned and would
    never notice recovery on its own.
    """
    import subprocess
    import sys
    import tempfile

    out = Path(tempfile.mkdtemp()) / "probe.json"
    code = (
        "import json, time, sys\n"
        "t0 = time.time()\n"
        "try:\n"
        "    import jax, jax.numpy as jnp\n"
        "    x = jnp.ones((256, 256), jnp.bfloat16)\n"
        "    v = float((x @ x).sum())\n"
        "    r = {'ok': True, 'platform': jax.devices()[0].platform,\n"
        "         'elapsed_s': round(time.time() - t0, 1)}\n"
        "except Exception as e:\n"
        "    r = {'ok': False, 'error': repr(e)[:300],\n"
        "         'elapsed_s': round(time.time() - t0, 1)}\n"
        f"open({str(out)!r}, 'w').write(json.dumps(r))\n"
        "print(json.dumps(r))\n"
    )
    # The child must not inherit our stdout/stderr: a still-running
    # child would hold the caller's pipes open past our return.
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        # Deliberately NOT killed: detach and report unreachable.
        return {"ok": False, "error": f"probe still hung after {timeout_s}s "
                "(child left to exit on its own; relay likely wedged)"}
    if out.exists():
        return json.loads(out.read_text())
    return {"ok": False, "error": f"probe exited rc={proc.returncode} without a result"}


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--smoke", action="store_true", help="tiny CPU-safe run")
    parser.add_argument(
        "--probe", action="store_true",
        help="subprocess TPU health check (never wedges); prints one JSON line",
    )
    parser.add_argument(
        "--batch", type=int, default=None,
        help="per-chip batch size (default: 128 ResNet, 8 LM)",
    )
    parser.add_argument("--steps", type=int, default=None,
                        help="timed steps (default: 32 ResNet, 16 LM)")
    parser.add_argument(
        "--scan-chunk", type=int, default=None,
        help="train steps per dispatch, 1 = python loop "
        "(default: 16 ResNet, 8 LM)",
    )
    parser.add_argument(
        "--multihost", action="store_true",
        help="whole-slice data parallelism; launch per host via hops_tpu.launch "
        "(see RUNBOOK_v5e64.md)",
    )
    parser.add_argument(
        "--no-probe", action="store_true",
        help="skip the pre-run relay health probe (saves ~20s when known-healthy)",
    )
    parser.add_argument(
        "--grad-comms",
        choices=["none", "quantized", "zero1", "quantized+zero1",
                 "overlap", "quantized+overlap", "zero2",
                 "quantized+zero2", "zero3", "quantized+zero3",
                 "hier", "quantized+hier"],
        default="none",
        help="gradient-communication schedule for the ResNet bench: "
        "block-scaled int8 quantized all-reduce, ZeRO-1/2/3 sharded "
        "updates, overlap-scheduled (bucket-as-ready, launched "
        "under backward) variants, and hierarchy-aware (intra-host "
        "reduce, one inter-host exchange per byte) schedules "
        "(hops_tpu.parallel.grad_comms); overlap/zero2/zero3 lines "
        "carry overlap_fraction and per-chip optimizer-state bytes",
    )
    parser.add_argument(
        "--remat", action="store_true",
        help="per-block rematerialization: trade recompute FLOPs for "
        "activation HBM bytes (A/B lever on the bandwidth-bound step)",
    )
    parser.add_argument(
        "--input-pipeline", choices=["sync", "threaded"], default=None,
        help="host input-pipeline bench (featurestore/loader.py): "
        "decode-heavy RecordIO feed, sync = single-threaded reference, "
        "threaded = staged pool pipeline; reports pipeline samples/s "
        "and starved-step fraction; host-only (no accelerator, no "
        "relay lock)",
    )
    parser.add_argument(
        "--online-store", action="store_true",
        help="online feature-store tier: batched entity-ID joins "
        "against the sharded store while a pubsub write-through "
        "materializer streams updates; reports lookup QPS, join "
        "p50/p99 latency, hit rate, and freshness lag; host-only "
        "(no accelerator, no relay lock)",
    )
    parser.add_argument(
        "--serving-fleet", action="store_true",
        help="serving-fleet tier: N replicas behind the least-loaded "
        "router vs a single replica, under closed-loop client load "
        "with autoscale-up and a mid-load rollout; reports requests/s, "
        "p50/p99 latency, per-replica balance, scale events, and the "
        "rollout blip; host-only (no accelerator, no relay lock)",
    )
    parser.add_argument(
        "--multi-host", action="store_true", dest="multi_host",
        help="multi-host placement tier: hostd-placed replicas and "
        "placed feature shards vs their local-placement baselines "
        "(fleet rps/p99 local vs placed, shard multi_get fan-out "
        "local vs placed, warm-start row-identity check, placement "
        "control-plane RPC count); host-only (no accelerator, no "
        "relay lock)",
    )
    parser.add_argument(
        "--partition", action="store_true",
        help="partition-tolerance chaos drill: asymmetric network cut "
        "of a host carrying a placed replica + feature shard, with "
        "MTTR decomposed (time-to-re-place after the generation fence, "
        "heal-to-zombie-410, lease-expiry time-to-self-fence); asserts "
        "zero client-visible errors and a clean slot-invariant audit; "
        "host-only (no accelerator, no relay lock)",
    )
    parser.add_argument(
        "--tail", action="store_true",
        help="tail-robustness tier: Poisson load against a fleet with "
        "an injected slow-not-dead replica (hedging + outlier ejection "
        "vs bare: p50/p99/p999, hedge budget spend), a slow feature "
        "shard (sequential vs parallel fan-out + hedge), and a "
        "QoS/brownout overload phase (per-class latency, shed mix); "
        "host-only (no accelerator, no relay lock)",
    )
    parser.add_argument(
        "--continuous-loop", action="store_true",
        help="continuous-training tier: pubsub topic -> streaming "
        "trainer under the exactly-once span ledger -> eval gate -> "
        "registry push -> breaker-judged fleet rollout, with client "
        "load throughout, one injected transient broker fault, and one "
        "poisoned eval gate (forced rollback); reports spans/s "
        "trained, freshness lag, eval-gate latency, cutover blip, and "
        "recovery counts; host-only (JAX pinned to CPU, no relay lock)",
    )
    parser.add_argument(
        "--fault-overhead", action="store_true",
        help="measure the DISARMED faultinject.fire() cost on the hot "
        "paths (ns/call vs an empty loop); host-only, guards the "
        "zero-overhead-when-disarmed contract",
    )
    parser.add_argument(
        "--tracing-overhead", action="store_true",
        help="measure the request-tracing plumbing cost on the serving "
        "hot path: disabled (ns/span), enabled-but-untraced (ns/span), "
        "and fully sampled (us/trace); host-only, guards the "
        "tracing-disabled-is-free contract",
    )
    parser.add_argument(
        "--capture-overhead", action="store_true",
        help="measure the DISABLED workload-capture cost on the request "
        "paths (ns/check vs an empty loop); host-only, guards the "
        "capture-disabled-is-free contract",
    )
    parser.add_argument(
        "--hot-path", action="store_true",
        help="micro-tier for the serving hot path: router relay "
        "ns/request (json round-trip vs zero-copy), online-store "
        "lookup ns (sqlite vs native), KV quant/dequant ns/block, "
        "batch-assembly reuse hit rate, and HTTP transport us/request "
        "(stdlib thread-per-connection vs the shared event-loop core); "
        "host-only",
    )
    parser.add_argument(
        "--replay", metavar="ARTIFACT", default=None,
        help="workload-replay tier: re-issue a captured workload "
        "artifact (telemetry/workload capture dir) open-loop against "
        "an in-process serving fleet; reports recorded-vs-replayed "
        "status mix / throughput / latency and arrival fidelity; "
        "host-only (no accelerator, no relay lock)",
    )
    parser.add_argument(
        "--replay-scenario",
        choices=["diurnal", "herd", "hot_key", "tenant_spray"],
        default=None,
        help="synthesize this scenario artifact and replay it (instead "
        "of --replay PATH); captured and synthetic workloads share one "
        "replay path",
    )
    parser.add_argument(
        "--replay-speed", type=float, default=1.0,
        help="replay time-compression: recorded inter-arrivals are "
        "divided by this (2.0 = yesterday's traffic at double speed)",
    )
    parser.add_argument(
        "--replay-seed", type=int, default=0,
        help="seed for deterministic re-materialization of capped "
        "payloads (same artifact + seed = identical issued stream)",
    )
    parser.add_argument(
        "--lm", action="store_true",
        help="LM training headline instead of ResNet-50: ~180M-param "
        "TransformerLM (d_head 128, flash attention, chunked LM-head "
        "loss, bf16), reporting tokens/s/chip and MFU%%",
    )
    parser.add_argument(
        "--seq-len", type=int, default=1024, help="--lm sequence length"
    )
    parser.add_argument(
        "--lm-serving", action="store_true",
        help="LM serving-engine tier: paged KV cache + chunked prefill "
        "vs the dense full-prefill baseline at equal cache memory, "
        "under a seeded Poisson arrival load; reports tokens/s/chip, "
        "TTFT p50/p99, slot occupancy, block-pool utilization, and "
        "preempted-prefill counts",
    )
    parser.add_argument(
        "--lm-serving-requests", type=int, default=48,
        help="--lm-serving: requests in the Poisson workload",
    )
    parser.add_argument(
        "--lm-serving-rate", type=float, default=None,
        help="--lm-serving: Poisson arrival rate (req/s; default "
        "platform-tuned)",
    )
    parser.add_argument(
        "--lm-serving-tp", action="store_true",
        help="--lm-serving: run both engines tensor-parallel over all "
        "visible devices (parallel/tp_inference; paged pools "
        "head-sharded)",
    )
    parser.add_argument(
        "--lock-wait", type=float, default=900.0,
        help="seconds to wait for the relay lock before falling back to "
        "the last green logged result (stale-flagged)",
    )
    args = parser.parse_args()

    import os

    from hops_tpu.runtime.relaylock import ENV_TOKEN, RelayBusy, current_owner, relay_lock

    if args.fault_overhead:
        result = run_fault_overhead_bench()
        print(json.dumps({"metric": "faultinject_disarmed_ns_per_call",
                          "value": result["ns_per_disarmed_fire"],
                          "unit": "ns", **result}))
        return

    if args.tracing_overhead:
        result = run_tracing_overhead_bench()
        print(json.dumps({"metric": "tracing_disabled_ns_per_span",
                          "value": result["ns_per_disabled_span"],
                          "unit": "ns", **result}))
        return

    if args.capture_overhead:
        result = run_capture_overhead_bench()
        print(json.dumps({"metric": "workload_capture_disabled_ns_per_check",
                          "value": result["ns_per_disabled_check"],
                          "unit": "ns", **result}))
        return

    if args.hot_path:
        # Host-only micro tier: no accelerator, no relay lock.
        _note("hot-path micro bench: relay / lookup / kv-quant / assembly / transport")
        result = run_hot_path_bench(smoke=args.smoke)
        print(json.dumps({
            "metric": "hot_path_relay_saved_ns_per_request",
            "value": result["relay_saved_ns_per_request"],
            "unit": "ns",
            **result,
        }))
        return

    if args.replay or args.replay_scenario:
        # Entirely host-side, like --serving-fleet: no accelerator
        # touch, no relay lock, no TPU probe.
        _note("workload-replay bench: captured/synthetic stream vs live fleet")
        result = run_workload_replay_bench(
            artifact=args.replay,
            scenario=args.replay_scenario,
            speed=args.replay_speed,
            seed=args.replay_seed,
            smoke=args.smoke,
        )
        print(json.dumps({
            "metric": "workload_replay_requests_per_sec",
            "value": result["replayed"]["rps"],
            "unit": "req/s",
            **result,
        }))
        return

    if args.continuous_loop:
        # Host-side loop, but the checkpoint layer initializes a JAX
        # backend — pin it to CPU so this tier never touches an
        # accelerator (and needs no relay lock).
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        _note("continuous-loop bench: stream -> train -> gate -> cutover")
        result = run_continuous_loop_bench(smoke=args.smoke)
        print(json.dumps({
            "metric": "continuous_loop_spans_per_sec",
            "value": result["spans_per_sec"],
            "unit": "spans/s",
            **result,
        }))
        return

    if args.tail:
        # Entirely host-side: no accelerator touch, no relay lock.
        _note("tail bench: gray replica + slow shard + QoS brownout")
        result = run_tail_bench(smoke=args.smoke)
        print(json.dumps({
            "metric": "tail_hedged_p99_improvement",
            "value": result["p99_improvement"],
            "unit": "x",
            **result,
        }))
        return

    if args.multi_host:
        # Entirely host-side: the hostds, placement client and shard
        # servers are all stdlib HTTP — no accelerator, no relay lock.
        _note("multi-host bench: hostd-placed fleet + shards vs local")
        result = run_multi_host_bench(smoke=args.smoke)
        print(json.dumps({
            "metric": "multi_host_placed_over_local",
            "value": result["placed_over_local"],
            "unit": "x",
            **result,
        }))
        return

    if args.partition:
        # Entirely host-side, like --multi-host: no accelerator touch,
        # no relay lock.
        _note("partition bench: asymmetric cut -> fence -> re-place -> heal")
        result = run_partition_bench(smoke=args.smoke)
        print(json.dumps({
            "metric": "partition_time_to_replace_s",
            "value": result["time_to_replace_s"],
            "unit": "s",
            **result,
        }))
        return

    if args.serving_fleet:
        # Entirely host-side, like --online-store: no accelerator
        # touch, no relay lock, no TPU probe.
        _note("serving-fleet bench: routed replicas vs one, rollout mid-load")
        result = run_serving_fleet_bench(smoke=args.smoke)
        print(json.dumps({
            "metric": "serving_fleet_requests_per_sec",
            "value": result["requests_per_sec"],
            "unit": "req/s",
            **{k: result[k] for k in (
                "p50_ms", "p99_ms", "replicas", "clients", "work_ms",
                "balance_min_over_max", "scale_events_up",
                "rollout_outcome", "rollout_blip_ms", "errors",
                "single_replica_rps", "speedup_vs_single",
            )},
        }))
        return

    if args.online_store:
        # Entirely host-side, like --input-pipeline: no accelerator
        # touch, no relay lock, no TPU probe.
        _note("online-store bench: sharded joins under write-through load")
        result = run_online_store_bench(smoke=args.smoke)
        print(json.dumps({
            "metric": "online_store_lookup_qps",
            "value": round(result["lookup_qps"], 1),
            "unit": "lookups/s",
            **{k: result[k] for k in (
                "join_p50_ms", "join_p99_ms", "hit_rate", "freshness_lag_s",
                "materialized_rows", "entities", "shards", "readers",
                "write_rps",
            )},
        }))
        return

    if args.input_pipeline:
        # Entirely host-side: no accelerator touch, so no relay lock
        # and no TPU probe. The threaded run also times the sync
        # reference so its line carries the speedup attribution.
        _note(f"input-pipeline bench: mode={args.input_pipeline}")
        result = run_input_pipeline_bench(args.input_pipeline)
        line = {
            "metric": "input_pipeline_samples_per_sec",
            "value": round(result["samples_per_sec"], 2),
            "unit": "samples/s",
            "mode": result["mode"],
            "starved_frac": result["starved_frac"],
            "workers": result["workers"],
        }
        if args.input_pipeline == "threaded":
            _note("timing the sync reference for speedup attribution")
            ref = run_input_pipeline_bench("sync", epochs=1)
            line["sync_samples_per_sec"] = round(ref["samples_per_sec"], 2)
            line["sync_starved_frac"] = ref["starved_frac"]
            line["speedup_vs_sync"] = round(
                result["samples_per_sec"] / ref["samples_per_sec"], 2)
        print(json.dumps(line))
        return

    if args.probe:
        # A probe during someone else's compile is itself a collision
        # risk, so a held lock answers "busy" WITHOUT touching the
        # relay. Lock holders' own probes (hw_watch) pass through via
        # the inherited token.
        owner = None if os.environ.get(ENV_TOKEN) else current_owner()
        if owner is not None:
            print(json.dumps({"metric": "tpu_probe", "ok": False, "busy": True,
                              "owner": owner}))
            return
        print(json.dumps({"metric": "tpu_probe", **probe_tpu()}))
        return

    if args.lm_serving:
        if args.multihost:
            parser.error(
                "--lm-serving --multihost is not supported: use "
                "--lm-serving-tp for the tensor-parallel variant on one "
                "host's devices"
            )
        metric, unit, value_key = (
            "lm_serving_tokens_per_sec_per_chip", "tokens/s/chip",
            "tokens_per_sec_per_chip",
        )

        def do_run(**overrides):
            overrides.pop("multihost", None)
            return run_lm_serving_bench(
                requests=args.lm_serving_requests,
                rate_rps=args.lm_serving_rate,
                tp=args.lm_serving_tp,
                **overrides,
            )
    elif args.lm:
        if args.multihost:
            parser.error(
                "--lm --multihost is not supported yet: the multihost LM "
                "path is exercised by dryrun_multichip and the multihost "
                "integration tests; the LM headline is single-chip"
            )
        if args.grad_comms != "none":
            parser.error(
                "--grad-comms applies to the ResNet data-parallel bench; "
                "the LM headline is single-chip (no gradient collective "
                "to optimize)"
            )
        metric, unit, value_key = "lm_tokens_per_sec_per_chip", "tokens/s/chip", "tokens_per_sec_per_chip"
        batch = args.batch if args.batch is not None else 8
        steps = args.steps if args.steps is not None else 16
        scan_chunk = args.scan_chunk if args.scan_chunk is not None else 8

        def do_run(**overrides):
            return run_lm_bench(
                per_chip_batch=batch, seq_len=args.seq_len, steps=steps,
                scan_chunk=scan_chunk, remat=args.remat, **overrides,
            )
    else:
        metric, unit, value_key = (
            "resnet50_samples_per_sec_per_chip", "samples/s/chip", "samples_per_sec_per_chip"
        )
        batch = args.batch if args.batch is not None else 128
        steps = args.steps if args.steps is not None else 32
        scan_chunk = args.scan_chunk if args.scan_chunk is not None else 16

        def do_run(**overrides):
            return run_bench(
                per_chip_batch=batch, steps=steps,
                scan_chunk=scan_chunk, remat=args.remat,
                grad_comms=args.grad_comms, **overrides,
            )

    if args.smoke:
        # The smoke run is documented CPU-safe; pin it there so it
        # never touches (or waits on) the single-tenant TPU relay —
        # and it needs no relay lock for the same reason. Env alone is
        # not enough when a sitecustomize pre-imported jax — same
        # trick as tests/conftest.py.
        jax.config.update("jax_platforms", "cpu")
        # --smoke --multihost is the two-OS-process integration test's
        # harness (launched via hops_tpu.launch on the fake mesh).
        result = do_run(smoke=True, **({"multihost": True} if args.multihost else {}))
    elif args.multihost:
        # Multihost runs are launched one-process-per-host by
        # hops_tpu.launch against a real slice (no shared relay);
        # serialization is the launcher's job, not this lock's.
        _enable_compile_cache()
        result = do_run(multihost=True)
    else:
        try:
            # The driver's round-end run would rather wait out a
            # sweep-in-progress than go red; 900 s covers the longest
            # observed warm-cache queue step.
            with relay_lock(f"bench.py {metric}", wait_s=args.lock_wait):
                if not args.no_probe:
                    # Fail over instead of hanging the driver: a wedged
                    # relay makes every backend call block forever, and
                    # killing the hung bench is what wedges the relay
                    # further. The probe runs under a bounded
                    # RetryPolicy + per-attempt deadline (the BENCH_r04/
                    # r05 fix: one open-ended 240 s wait wedged two
                    # rounds), and its failure KIND travels on the
                    # stale line.
                    _note("probing relay health before committing to the real run")
                    health, kind, err = probe_with_retry()
                    if health is None:
                        _note(f"relay unreachable ({kind}): {err}")
                        emit_stale_or_fail(
                            metric, f"relay unreachable: {err}", kind=kind
                        )
                    _note(f"relay healthy ({health.get('platform')}, {health.get('elapsed_s')}s)")
                _enable_compile_cache()
                result = do_run()
        except RelayBusy as e:
            _note(str(e))
            emit_stale_or_fail(
                metric, f"relay lock busy: {e.owner}", kind="relay_busy"
            )
    value = result[value_key]
    if args.multihost and jax.process_index() != 0:
        return  # one JSON line total: the chief's

    # Baselines are recorded per platform (and per benchmark: the LM
    # headline keys "<platform>_lm"): the first real run on a platform
    # becomes that platform's baseline; later runs report against it.
    baseline = None
    if not args.smoke:
        baseline_key = result["platform"] + (
            "_lmserv" if args.lm_serving else ("_lm" if args.lm else "")
        )
        recorded = json.loads(BASELINE_FILE.read_text()) if BASELINE_FILE.exists() else {}
        entry = recorded.get(baseline_key)
        if entry is not None:
            baseline = entry.get(value_key)
        elif result.get("grad_comms", "none") != "none":
            # An optimized-comms run must not become the platform
            # baseline it is supposed to be compared against.
            baseline = None
        else:
            recorded[baseline_key] = {
                value_key: value,
                "platform": result["platform"],
                "recorded": time.strftime("%Y-%m-%d"),
            }
            BASELINE_FILE.write_text(json.dumps(recorded, indent=2))
            baseline = value

    line = {
        "metric": metric,
        "value": round(value, 2),
        "unit": unit,
        "vs_baseline": round(value / baseline, 4) if baseline else 1.0,
    }
    if result.get("grad_comms", "none") != "none":
        # Attribution: which comms schedule produced this number, and
        # how many wire bytes it saved (telemetry gauge's value).
        line.update(
            grad_comms=result["grad_comms"],
            grad_comms_compression=result["grad_comms_compression"],
        )
        if "opt_state_bytes_per_chip" in result:
            line["opt_state_bytes_per_chip"] = result["opt_state_bytes_per_chip"]
        if "overlap_fraction" in result:
            # The headline of the overlap-scheduled modes: comms time
            # hidden under backward / total comms time, with the raw
            # reference step times for the trajectory.
            line.update(
                overlap_fraction=result["overlap_fraction"],
                seq_step_time_ms=result["seq_step_time_ms"],
                nocomms_step_time_ms=result["nocomms_step_time_ms"],
            )
    if args.lm:
        # The roofline context travels with the number (review item #4:
        # "tokens/s/chip AND MFU% with the same roofline treatment").
        line.update(
            mfu_pct=result["mfu_pct"],
            model_tflops_per_sec_per_chip=result["model_tflops_per_sec_per_chip"],
            n_params_m=result["n_params_m"],
            seq_len=result["seq_len"],
        )
    if args.lm_serving:
        # The paged engine's headline plus the dense same-memory
        # baseline it beat — the comparison IS the measurement.
        line.update(
            engine="paged",
            ttft_p50_ms=result["ttft_p50_ms"],
            ttft_p99_ms=result["ttft_p99_ms"],
            slot_occupancy=result["slot_occupancy"],
            block_pool_peak_util=result["block_pool_peak_util"],
            prefill_chunks=result["prefill_chunks"],
            preempted_prefills=result["preempted_prefills"],
            dense_tokens_per_sec_per_chip=result["dense_tokens_per_sec_per_chip"],
            dense_ttft_p99_ms=result["dense_ttft_p99_ms"],
            speedup_vs_dense=result["speedup_vs_dense"],
            # int8 paged leg at the same byte budget: the capacity
            # headline (live tokens per pool) plus its throughput.
            int8_tokens_per_sec_per_chip=result["int8_tokens_per_sec_per_chip"],
            int8_ttft_p99_ms=result["int8_ttft_p99_ms"],
            int8_pool_blocks=result["int8_pool_blocks"],
            fp_pool_blocks=result["fp_pool_blocks"],
            int8_live_tokens_ratio=result["int8_live_tokens_ratio"],
            int8_block_pool_peak_util=result["int8_block_pool_peak_util"],
        )
    print(json.dumps(line))


if __name__ == "__main__":
    main()
