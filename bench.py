"""Benchmark harness — the TPU port of the reference's benchmark notebook.

Reference: notebooks/ml/Benchmarks/benchmark.ipynb — ResNet-50 on
synthetic 224x224x3 batches under MirroredStrategy, bs=8/GPU (SURVEY.md
§6). Here: ResNet-50 fwd+bwd+SGD on synthetic data, bf16 on the MXU,
per-chip batch sized for TPU (64 by default), data-parallel over all
visible chips.

Prints ONE JSON line:
  {"metric": "resnet50_samples_per_sec_per_chip", "value": N,
   "unit": "samples/s/chip", "vs_baseline": N}

The reference publishes no numbers (BASELINE.md), so the recorded
baseline is self-measured: the first TPU run's value is stored in
BASELINE_SELF.json and later rounds report improvement against it.
"""

from __future__ import annotations

import argparse
import json
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_FILE = Path(__file__).parent / "BASELINE_SELF.json"


def _sync(tree) -> float:
    """Force completion via a device-to-host transfer.

    ``jax.block_until_ready`` is unreliable on relayed backends (it can
    return before execution finishes); an actual value transfer cannot.
    """
    return float(jax.tree.leaves(tree)[0])


def run_bench(
    per_chip_batch: int = 128,  # measured sweet spot on v5e (64→1898, 128→2053, 256→1982 samples/s/chip)
    image_size: int = 224,
    steps: int = 30,
    warmup: int = 5,
    smoke: bool = False,
) -> dict:
    from hops_tpu.models import common
    from hops_tpu.models.resnet import ResNet18ish, ResNet50
    from hops_tpu.parallel.strategy import Strategy

    if smoke:
        model = ResNet18ish(dtype=jnp.float32)
        per_chip_batch, image_size, steps, warmup = 8, 32, 4, 1
    else:
        model = ResNet50(num_classes=1000)

    strategy = Strategy()  # data-parallel over all visible chips
    n_chips = strategy.num_replicas_in_sync
    global_batch = per_chip_batch * n_chips

    state = strategy.replicate(
        common.create_bn_train_state(
            model, jax.random.PRNGKey(0), (per_chip_batch, image_size, image_size, 3)
        )
    )
    step_fn = strategy.step(common.make_bn_train_step())

    rs = np.random.RandomState(0)
    batch = strategy.distribute_batch(
        {
            "image": rs.randn(global_batch, image_size, image_size, 3).astype(np.float32),
            "label": rs.randint(0, 10, (global_batch,)),
        }
    )

    for _ in range(warmup):
        state, metrics = step_fn(state, batch)
    _sync(metrics)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, batch)
    _sync(metrics)
    elapsed = time.perf_counter() - t0

    samples_per_sec = global_batch * steps / elapsed
    return {
        "samples_per_sec": samples_per_sec,
        "samples_per_sec_per_chip": samples_per_sec / n_chips,
        "step_time_ms": elapsed / steps * 1e3,
        "n_chips": n_chips,
        "global_batch": global_batch,
        "platform": jax.devices()[0].platform,
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--smoke", action="store_true", help="tiny CPU-safe run")
    parser.add_argument("--batch", type=int, default=128, help="per-chip batch size")
    parser.add_argument("--steps", type=int, default=30)
    args = parser.parse_args()

    result = run_bench(per_chip_batch=args.batch, steps=args.steps, smoke=args.smoke)
    value = result["samples_per_sec_per_chip"]

    # Baselines are recorded per platform: the first real run on a
    # platform becomes that platform's baseline; later runs report
    # against it.
    baseline = None
    if not args.smoke:
        recorded = json.loads(BASELINE_FILE.read_text()) if BASELINE_FILE.exists() else {}
        entry = recorded.get(result["platform"])
        if entry is not None:
            baseline = entry.get("samples_per_sec_per_chip")
        else:
            recorded[result["platform"]] = {
                "samples_per_sec_per_chip": value,
                "platform": result["platform"],
                "recorded": time.strftime("%Y-%m-%d"),
            }
            BASELINE_FILE.write_text(json.dumps(recorded, indent=2))
            baseline = value

    print(
        json.dumps(
            {
                "metric": "resnet50_samples_per_sec_per_chip",
                "value": round(value, 2),
                "unit": "samples/s/chip",
                "vs_baseline": round(value / baseline, 4) if baseline else 1.0,
            }
        )
    )


if __name__ == "__main__":
    main()
