"""Continuous training with eval-gated live cutover into the serving
fleet — the platform's closed loop.

The reference platform's defining property is not any one subsystem but
the loop through all of them: streaming ingest feeds training, training
feeds the model repo, the repo feeds serving, and the whole thing runs
*continuously* while brokers hiccup, trainers die, and bad candidates
appear. This module is that loop, built from the pieces the previous
PRs proved in isolation:

- **Streaming spans, exactly once.** A
  :class:`~hops_tpu.featurestore.StreamingSource` tails the topic with
  a durable consumer group. Delivery is at-least-once (the
  Materializer's offset discipline: commit only after the work is
  durable); convergence to *effectively-once training* comes from the
  :class:`SpanLedger` — a checkpoint-sidecar JSONL whose entries tile
  the consumed byte range of the topic. The group offset commits only
  AFTER the ledger entries covering it are fsynced next to the
  checkpoint, so a crash replays uncommitted spans and the ledger
  dedupes the overlap. The bar is the TensorFlow paper's: resume from
  consistent state without double-applying data.

- **The rollback protocol.** Model state and ledger move together:
  every checkpoint save flushes the ledger entries for the steps it
  contains, then commits the offset. A restore that falls back to step
  N truncates the ledger to entries with ``step <= N`` and repositions
  the stream at the truncated end — spans past N replay against the
  rolled-back state and land in the ledger exactly once. Provable from
  the file: entries are disjoint, contiguous, and step-monotonic
  (:meth:`SpanLedger.verify`).

- **Eval gate + cutover.** Every ``eval_every`` steps the segment ends,
  a held-out eval scores the candidate, and only an improvement (per
  ``mode``/``min_delta``) is pushed to the model registry and rolled
  into the serving fleet via the breaker-judged rollout
  (:mod:`hops_tpu.modelrepo.fleet.rollout`) — which itself rolls back
  on a canary breaker trip. An eval regression never reaches the
  fleet; a breaker-tripped canary never replaces the incumbent. Both
  outcomes land on the flight recorder (``eval_gate`` / ``cutover``
  events) and on metrics.

Chaos-proven end to end in ``tests/test_continuous.py``: broker faults,
poison records, a SIGKILLed trainer mid-span, and a mid-rollout replica
kill, with the ledger accounting every span exactly once and zero
client-visible serving errors. Benchmarked by
``bench.py --continuous-loop``.
"""

from __future__ import annotations

import bisect
import dataclasses
import json
import os
import threading
import time
from pathlib import Path
from typing import Any, Callable

import numpy as np

from hops_tpu.runtime import flight
from hops_tpu.runtime.logging import get_logger
from hops_tpu.runtime.preemption import PreemptionGuard, run_preemptible
from hops_tpu.telemetry.metrics import REGISTRY

log = get_logger(__name__)

LEDGER_FILENAME = "span_ledger.jsonl"

_m_records = REGISTRY.counter(
    "hops_tpu_continuous_records_total",
    "Streamed records seen by the continuous trainer, by disposition "
    "(trained = entered a span ledger entry, deduped = replayed offsets "
    "the ledger already covered)",
    labels=("result",),
)
_m_spans = REGISTRY.counter(
    "hops_tpu_continuous_spans_trained_total",
    "Training spans (ledger entries) the continuous loop produced",
)
_m_gates = REGISTRY.counter(
    "hops_tpu_continuous_eval_gates_total",
    "Eval-gate decisions on continuous-training candidates",
    labels=("outcome",),
)
_m_cutovers = REGISTRY.counter(
    "hops_tpu_continuous_cutovers_total",
    "Candidate cutovers into the registry/fleet, by rollout outcome "
    "(pushed = registry only, completed / rolled_back = fleet rollout)",
    labels=("outcome",),
)
_m_gate_seconds = REGISTRY.histogram(
    "hops_tpu_continuous_eval_gate_seconds",
    "Held-out eval latency per gate (training is paused while it runs)",
    buckets=(0.001, 0.005, 0.025, 0.1, 0.5, 2.0, 10.0, 60.0),
)


# -- the span ledger ----------------------------------------------------------


@dataclasses.dataclass
class SpanEntry:
    """One trained span: a byte range of the topic log and the training
    step whose update contains it."""

    first: int  #: starting byte offset of the span (inclusive)
    last: int  #: ending byte offset (exclusive — the next span's first)
    records: int  #: records actually trained (poison records excluded)
    step: int  #: the training step that consumed this span

    def to_json(self) -> str:
        return json.dumps({"first": self.first, "last": self.last,
                           "records": self.records, "step": self.step},
                          separators=(",", ":"))


class SpanLedger:
    """The durable account of what training has consumed.

    A JSONL sidecar (``span_ledger.jsonl``) in the checkpoint directory:
    one :class:`SpanEntry` per line, appended with flush + fsync BEFORE
    the consumer offset commits. Entries tile the consumed byte range of
    the topic contiguously and disjointly, in step order — which makes
    exactly-once training *provable from the file* (:meth:`verify`)
    rather than asserted by the code that must uphold it.

    Crash windows, by construction:

    - torn final line (died mid-append): the entry was not durable, the
      offset was not committed — the span replays and re-appends; the
      torn tail is truncated on load.
    - entries flushed, commit missed: replayed records are covered
      (``offset < end_offset``) and deduped by the stream.
    - checkpoint fell back to step N: :meth:`truncate_to_step` drops
      the orphaned ``step > N`` entries (their updates are not in the
      restored state) and the spans re-train, re-appending once.

    **Compaction** (:meth:`compact`): the ledger would otherwise grow
    one line per span for the loop's lifetime. Fully-committed history
    — entries no restorable checkpoint can ever roll back behind — is
    folded into a single *base line* at the top of the file
    (``{"compact": 1, first, last, records, step, entries}``): the
    covered range, record count, and provability survive (``verify``
    still proves contiguity ACROSS the compaction boundary: the first
    retained entry must continue exactly at the base's ``last``), only
    the per-span granularity of the folded prefix is given up. The
    caller chooses the fold horizon; it must be ≤ the oldest step a
    checkpoint restore could land on (``truncate_to_step`` below a
    compacted base cannot un-fold — it warns loudly and keeps the
    base, because the folded spans' updates are in every restorable
    checkpoint by the caller's own contract).

    Single-writer by contract (the training loop); readers (tests,
    accounting) may open their own instance against the same file.
    """

    def __init__(self, directory: str | Path):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.path = self.directory / LEDGER_FILENAME
        self._entries: list[SpanEntry] = []
        self._base: SpanEntry | None = None  # folded history (compaction)
        self._base_folded = 0  # entries the base line stands for
        self._load()

    def _load(self) -> None:
        if not self.path.exists():
            return
        raw = self.path.read_bytes()
        good_bytes = 0
        for i, line in enumerate(raw.splitlines(keepends=True)):
            if not line.endswith(b"\n"):
                break  # torn tail: the append died mid-line
            try:
                d = json.loads(line)
                if d.get("compact"):
                    # The base-offset line: valid only as line 0 (it is
                    # written only by the atomic compaction rewrite).
                    if i != 0:
                        break
                    self._base = SpanEntry(
                        first=int(d["first"]), last=int(d["last"]),
                        records=int(d["records"]), step=int(d["step"]))
                    self._base_folded = int(d.get("entries", 0))
                    good_bytes += len(line)
                    continue
                entry = SpanEntry(first=int(d["first"]), last=int(d["last"]),
                                  records=int(d["records"]),
                                  step=int(d["step"]))
            except (ValueError, KeyError, TypeError):
                break  # treat an unparsable line like a torn tail
            self._entries.append(entry)
            good_bytes += len(line)
        if good_bytes < len(raw):
            log.warning(
                "span ledger %s: truncating %d torn byte(s) after %d valid "
                "entries (the crash that tore it also left the span "
                "uncommitted — it will replay)",
                self.path, len(raw) - good_bytes, len(self._entries))
            with self.path.open("r+b") as f:
                f.truncate(good_bytes)
                f.flush()
                os.fsync(f.fileno())

    # -- reads ---------------------------------------------------------------

    @property
    def entries(self) -> list[SpanEntry]:
        return list(self._entries)

    @property
    def base(self) -> SpanEntry | None:
        """The compaction base: folded fully-committed history, or None
        when the ledger has never been compacted."""
        return self._base

    def __len__(self) -> int:
        return len(self._entries)

    def start_offset(self) -> int | None:
        if self._base is not None:
            return self._base.first
        return self._entries[0].first if self._entries else None

    def end_offset(self) -> int | None:
        """The exclusive end of the covered range — the offset training
        is durably caught up to (commit target)."""
        if self._entries:
            return self._entries[-1].last
        return self._base.last if self._base is not None else None

    def covered(self, offset: int) -> bool:
        """Is a record starting at ``offset`` inside a trained span?"""
        if (self._base is not None
                and self._base.first <= offset < self._base.last):
            return True
        firsts = [e.first for e in self._entries]
        i = bisect.bisect_right(firsts, offset) - 1
        return i >= 0 and offset < self._entries[i].last

    def records_total(self) -> int:
        base = self._base.records if self._base is not None else 0
        return base + sum(e.records for e in self._entries)

    # -- writes --------------------------------------------------------------

    def append(self, entries: list[SpanEntry]) -> None:
        """Durably append ``entries`` (flush + fsync) — the caller may
        commit the consumer offset once this returns."""
        if not entries:
            return
        prev_end = self.end_offset()
        for e in entries:
            if prev_end is not None and e.first != prev_end:
                raise ValueError(
                    f"span ledger {self.path}: entry [{e.first}, {e.last}) "
                    f"does not continue the covered range ending at "
                    f"{prev_end} — coverage must stay contiguous")
            prev_end = e.last
        with self.path.open("ab") as f:
            for e in entries:
                f.write(e.to_json().encode() + b"\n")
            f.flush()
            os.fsync(f.fileno())
        self._entries.extend(entries)

    def _rewrite(self) -> None:
        """Atomically rewrite the file from memory (base line first)."""
        tmp = self.path.with_suffix(".jsonl.tmp")
        with tmp.open("wb") as f:
            if self._base is not None:
                b = self._base
                f.write(json.dumps(
                    {"compact": 1, "first": b.first, "last": b.last,
                     "records": b.records, "step": b.step,
                     "entries": self._base_folded},
                    separators=(",", ":")).encode() + b"\n")
            for e in self._entries:
                f.write(e.to_json().encode() + b"\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def truncate_to_step(self, step: int) -> int:
        """Drop entries trained after checkpoint ``step`` (their updates
        are not in the restored state and their spans will replay).
        Returns the number of entries dropped."""
        if self._base is not None and step < self._base.step:
            # The restore landed BEHIND compacted history. Compaction's
            # contract (fold only steps every restorable checkpoint
            # already contains) makes this unreachable in a correct
            # deployment; if it happens anyway, the folded spans cannot
            # be un-folded — keep the base, shout, and let the stream
            # resume from its end rather than double-train the fold.
            log.error(
                "span ledger %s: restore at step %d is behind the "
                "compaction base (step %d) — compacted spans cannot "
                "replay; resuming from the base boundary",
                self.path, step, self._base.step)
            step = self._base.step
        keep = [e for e in self._entries if e.step <= step]
        dropped = len(self._entries) - len(keep)
        if dropped:
            self._entries = keep
            self._rewrite()
            log.warning(
                "span ledger %s: truncated %d entr%s past step %d — their "
                "spans replay against the restored state",
                self.path, dropped, "y" if dropped == 1 else "ies", step)
        return dropped

    def compact(self, up_to_step: int, retain_entries: int = 8) -> int:
        """Fold entries with ``step <= up_to_step`` into the base line
        (always retaining the newest ``retain_entries`` for span-level
        forensics). ``up_to_step`` MUST be at most the oldest step a
        checkpoint restore can land on — folded spans can never be
        truncated back out. Returns entries folded. Crash-safe: the
        rewrite is atomic (tmp + fsync + rename), so a crash leaves
        either the old or the new file, both self-consistent."""
        foldable = [e for e in self._entries if e.step <= int(up_to_step)]
        if retain_entries > 0:
            foldable = foldable[:max(0, len(self._entries) - retain_entries)]
        if not foldable:
            return 0
        first = self._base.first if self._base is not None else foldable[0].first
        records = (self._base.records if self._base is not None else 0)
        records += sum(e.records for e in foldable)
        self._base = SpanEntry(
            first=first, last=foldable[-1].last, records=records,
            step=foldable[-1].step)
        self._base_folded += len(foldable)
        self._entries = self._entries[len(foldable):]
        self._rewrite()
        log.info(
            "span ledger %s: compacted %d entr%s into base [%d, %d) "
            "(%d live lines remain)",
            self.path, len(foldable), "y" if len(foldable) == 1 else "ies",
            self._base.first, self._base.last, len(self._entries))
        return len(foldable)

    def reset(self) -> None:
        """Fresh start (step 0 with no checkpoint): nothing trained is
        durable, so nothing may stay accounted."""
        if self._entries or self._base is not None:
            log.warning("span ledger %s: reset discarded %d entries (fresh "
                        "start with no restorable checkpoint)", self.path,
                        len(self._entries) + self._base_folded)
        self._entries = []
        self._base = None
        self._base_folded = 0
        if self.path.exists():
            self.path.unlink()

    # -- the proof -----------------------------------------------------------

    def verify(self) -> dict[str, Any]:
        """The exactly-once accounting: entries must be contiguous
        (every byte of the consumed range in exactly one span),
        disjoint (no byte twice), and step-monotonic — INCLUDING across
        the compaction boundary: the first retained entry must continue
        exactly at the base's end, at a step not before the base's. The
        chaos e2e asserts this plus external coverage (every published
        record's offset inside the range, counts matching)."""
        contiguous = disjoint = steps_monotonic = True
        chain = (
            [self._base] if self._base is not None else []
        ) + self._entries
        for a, b in zip(chain, chain[1:]):
            if b.first != a.last:
                contiguous = False
            if b.first < a.last:
                disjoint = False
            if b.step < a.step:
                steps_monotonic = False
        return {
            "entries": len(self._entries),
            "compacted_entries": self._base_folded,
            "records": self.records_total(),
            "start": self.start_offset(),
            "end": self.end_offset(),
            "contiguous": contiguous,
            "disjoint": disjoint,
            "steps_monotonic": steps_monotonic,
        }


# -- the span stream ----------------------------------------------------------


class SpanStream:
    """The resumable batch stream ``run_preemptible`` trains on.

    Implements both halves of the loop's batches contract: it is the
    *callable* (``stream(start)`` repositions from the ledger and
    returns itself) and the *resumable iterator* (``state_dict`` /
    ``load_state_dict``). The positioning protocol:

    - ``stream(0)`` (no restorable checkpoint): reset the ledger and
      rewind the source to its initial offset — everything replays into
      the fresh state.
    - ``stream(start > 0)`` (restored at ``start - 1``): truncate the
      ledger to ``step <= start - 1`` and position the source at the
      truncated end — the committed group offset is never trusted past
      a restore, the ledger is the authority.
    - ``state_dict()`` is called by ``run_preemptible`` right after a
      checkpoint save lands: it flushes the pending ledger entries
      (fsync) and THEN commits the group offset — the at-least-once
      order the whole design hangs on.

    ``__next__`` polls the streaming source until at least
    ``min_records`` fresh (non-deduped) records arrive, collates them
    into one batch, and stages the span's ledger entry. Segment
    boundaries: iteration stops at the next ``eval_every`` multiple (the
    eval gate runs between segments), at ``max_steps``, on
    ``stop_when()``, or — with ``stop_on_idle`` — once the topic stays
    drained for ``idle_grace_s``.
    """

    def __init__(
        self,
        source: Any,
        directory: str | Path,
        *,
        collate: Callable[[list], Any] | None = None,
        min_records: int = 1,
        max_records: int = 256,
        eval_every: int = 50,
        max_steps: int | None = None,
        poll_interval_s: float = 0.02,
        stop_when: Callable[[], bool] | None = None,
        stop_on_idle: bool = False,
        idle_grace_s: float = 1.0,
        compact_after: int | None = 1024,
        compact_keep_steps: int | None = None,
    ):
        if min_records < 1 or max_records < min_records:
            raise ValueError(
                f"need 1 <= min_records <= max_records, got "
                f"{min_records}/{max_records}")
        if eval_every < 1:
            raise ValueError(f"eval_every must be >= 1, got {eval_every}")
        if compact_after is not None and compact_after < 1:
            raise ValueError(
                f"compact_after must be >= 1 or None, got {compact_after}")
        self.source = source
        self.ledger = SpanLedger(directory)
        self.collate = collate
        self.min_records = min_records
        self.max_records = max_records
        self.eval_every = eval_every
        self.max_steps = max_steps
        self.poll_interval_s = poll_interval_s
        self.stop_when = stop_when
        self.stop_on_idle = stop_on_idle
        self.idle_grace_s = idle_grace_s
        # Ledger compaction: once the ledger holds more than
        # `compact_after` live lines, history older than
        # `compact_keep_steps` (default: generous — 20x the checkpoint
        # retention window of max_to_keep=3 eval segments) folds into
        # the base line. None disables.
        self.compact_after = compact_after
        self.compact_keep_steps = (
            compact_keep_steps if compact_keep_steps is not None
            else 60 * eval_every)
        self._initial_offset = source.offset
        self._step = 0
        self._segment_end = eval_every
        self._pending: list[SpanEntry] = []
        # The next byte the ledger does NOT yet cover (pending entries
        # included). Entries always start here, so coverage tiles every
        # consumed byte — even across polls that consumed only poison
        # records and parsed nothing.
        self._cursor = self._initial_offset
        self.finished = False  # a terminal stop (idle/max_steps/stop_when)

    # -- run_preemptible's callable-batches contract --------------------------

    def __call__(self, start: int) -> "SpanStream":
        self._pending.clear()
        if start == 0:
            # Fresh state: nothing the ledger accounts is in it. A
            # restarted process whose checkpoints were ALL lost still
            # holds the committed group offset — rewind to the ledger's
            # own start so the dead incarnation's spans retrain instead
            # of silently vanishing into a zero state.
            ledger_start = self.ledger.start_offset()
            self.ledger.reset()
            self.source.offset = (self._initial_offset if ledger_start is None
                                  else min(self._initial_offset, ledger_start))
        else:
            self.ledger.truncate_to_step(start - 1)
            end = self.ledger.end_offset()
            # The ledger is the restore authority: reposition at its
            # truncated end regardless of what the group offset or the
            # in-memory consumer position say.
            self.source.offset = end if end is not None else self._initial_offset
        self._cursor = self.source.offset
        self._step = start
        self._segment_end = ((start // self.eval_every) + 1) * self.eval_every
        if self.max_steps is not None:
            self._segment_end = min(self._segment_end, self.max_steps)
        return self

    # -- resumable-iterator contract ------------------------------------------

    def state_dict(self) -> dict:
        """Flush pending spans to the ledger, commit the offset, and
        snapshot the position. Called by ``run_preemptible`` right
        after the checkpoint save for the current step — the ledger
        entries become durable WITH the checkpoint, and only then does
        the group offset move."""
        if self._pending:
            self.ledger.append(self._pending)
            _m_spans.inc(len(self._pending))
            self._pending.clear()
        if (self.compact_after is not None
                and len(self.ledger) > self.compact_after):
            # Fold only steps far behind anything a checkpoint restore
            # could land on (the compaction contract): the ledger stops
            # growing a line per span forever, exactly-once stays
            # provable across the fold.
            self.ledger.compact(self._step - self.compact_keep_steps)
        end = self.ledger.end_offset()
        if end is not None:
            self.source.offset = max(int(self.source.offset), end)
        self.source.commit()
        return {"version": 1, "offset": int(self.source.offset),
                "step": self._step}

    def load_state_dict(self, state: dict) -> None:
        # __call__ already repositioned from the ledger; the sidecar
        # only cross-checks. A mismatch means the sidecar and the
        # ledger disagree about the same save — the ledger (fsynced
        # first) wins, loudly.
        if int(state.get("offset", -1)) != int(self.source.offset):
            log.warning(
                "span stream: data-state sidecar offset %s disagrees with "
                "the ledger position %s — trusting the ledger",
                state.get("offset"), self.source.offset)
        self._step = int(state.get("step", self._step))

    # -- iteration ------------------------------------------------------------

    def __iter__(self) -> "SpanStream":
        return self

    def __next__(self) -> Any:
        if self.finished:
            raise StopIteration
        if self.max_steps is not None and self._step >= self.max_steps:
            self.finished = True
            raise StopIteration
        if self._step >= self._segment_end:
            raise StopIteration  # segment boundary: the eval gate runs now
        values: list = []
        last: int | None = None
        deduped = 0
        idle_since: float | None = None
        while len(values) < self.min_records:
            if self.stop_when is not None and self.stop_when():
                self.finished = True
                if not values:
                    raise StopIteration
                break
            span = self.source.poll_span(self.max_records - len(values))
            if span is None:
                if values:
                    break  # train what arrived rather than hold the step
                if self.stop_on_idle and self.source.lag() == 0:
                    now = time.monotonic()
                    if idle_since is None:
                        idle_since = now
                    elif now - idle_since >= self.idle_grace_s:
                        self.finished = True
                        raise StopIteration
                time.sleep(self.poll_interval_s)
                continue
            idle_since = None
            # Dedupe against the coverage cursor (flushed ledger +
            # pending entries): replayed offsets below it are already
            # in the trained state.
            fresh = [(o, v) for o, v in zip(span.offsets, span.values)
                     if o >= self._cursor]
            deduped += span.records - len(fresh)
            last = span.last
            values.extend(v for _, v in fresh)
        if deduped:
            _m_records.inc(deduped, result="deduped")
            flight.record("span_replayed", stream=getattr(
                self.source, "name", "?"), deduped=deduped, step=self._step)
        if not values:
            raise StopIteration
        _m_records.inc(len(values), result="trained")
        # The entry starts at the cursor, not at the first parsed
        # record: consumed-but-unparsable bytes (poison at the head of
        # a poll, or a whole poisoned poll) stay inside the covered
        # range, or the ledger's contiguity invariant would wedge the
        # loop on exactly the wire corruption it exists to survive.
        self._pending.append(SpanEntry(
            first=int(self._cursor), last=int(last), records=len(values),
            step=self._step))
        self._cursor = int(last)
        self._step += 1
        return self.collate(values) if self.collate is not None else values


# -- publishing ----------------------------------------------------------------


class RegistryFleetPublisher:
    """Push a passing candidate to the model registry and roll it into
    the serving fleet (PR 9's breaker-judged rollout — automatic
    rollback on a canary breaker trip is its designed recovery path).

    ``export_fn(state, step, metric) -> model meta`` registers the
    version (``registry.export`` / ``registry.save_flax`` — the caller
    owns the artifact format); with a ``fleet`` handle the new version
    is then rolled out. Without one, publishing stops at the registry
    (the cutover outcome is ``pushed``).
    """

    def __init__(self, name: str,
                 export_fn: Callable[[Any, int, float], dict],
                 fleet: Any = None,
                 rollout_kwargs: dict[str, Any] | None = None):
        self.name = name
        self.export_fn = export_fn
        self.fleet = fleet
        self.rollout_kwargs = dict(rollout_kwargs or {})

    def publish(self, state: Any, step: int, metric: float) -> dict[str, Any]:
        meta = self.export_fn(state, step, metric)
        version = meta.get("version") if isinstance(meta, dict) else None
        result: dict[str, Any] = {"version": version, "outcome": "pushed"}
        if self.fleet is not None:
            summary = self.fleet.roll_out(version, **self.rollout_kwargs)
            result["outcome"] = summary["outcome"]
            result["rollout"] = summary
        return result


class _CutoverWorker:
    """FIFO background driver for cutover rollouts.

    A passed gate used to run ``publisher.publish`` inline, pausing
    training for the whole registry push + canary-judged fleet rollout
    (~2 s per passed gate on the CPU tier) — visible as a freshness-lag
    dip at the following gate. This worker moves the publish onto ONE
    background thread so the next segment trains while the fleet bakes
    the canary.

    Semantics are preserved exactly, not approximately:

    - **Version order**: one thread, one queue — rollouts reach the
      fleet in gate order, never interleaved.
    - **The comparison bar**: results are NOT folded into ``best`` by
      the worker. The training loop calls :meth:`drain` right before
      judging the next gate (and once more before returning), so every
      gate decision sees all prior cutover outcomes — the same
      happens-before as the inline call, minus the training pause.
    - **Failures**: an exception from ``publish`` is re-raised out of
      :meth:`drain` on the training thread, where the inline version
      would have raised it; results that completed first still fold.

    ``state`` is captured by reference at submit time — safe because
    the training loop never mutates a state in place (train_step
    returns a fresh pytree; the reference the gate judged is the
    reference the publisher exports).
    """

    def __init__(self, publisher: "RegistryFleetPublisher"):
        self._publisher = publisher
        self._cond = threading.Condition()
        self._queue: list[tuple[Any, int, float] | None] = []  # guarded by: self._cond
        self._results: list[tuple[int, float, dict | None, BaseException | None]] = []  # guarded by: self._cond
        self._inflight = 0  # guarded by: self._cond
        self._thread = threading.Thread(
            target=self._run, name="continuous-cutover", daemon=True)
        self._thread.start()

    def submit(self, state: Any, step: int, metric: float) -> None:
        with self._cond:
            self._queue.append((state, step, metric))
            self._inflight += 1
            self._cond.notify()

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue:
                    self._cond.wait()
                item = self._queue.pop(0)
            if item is None:
                return
            state, step, metric = item
            cut: dict | None = None
            err: BaseException | None = None
            try:
                cut = self._publisher.publish(state, step, metric)
            except BaseException as e:  # noqa: BLE001 — surfaced via drain()
                err = e
            with self._cond:
                self._results.append((step, metric, cut, err))
                self._inflight -= 1
                self._cond.notify_all()

    def drain(self) -> list[tuple[int, float, dict | None, BaseException | None]]:
        """Block until every submitted cutover has settled; return the
        ``(step, metric, result, error)`` tuples in submission order.
        The caller folds successful results into its bookkeeping and
        then re-raises the first error — so cutovers that completed
        before a failing publish are never lost, exactly as if each had
        run inline."""
        with self._cond:
            while self._inflight > 0:
                self._cond.wait()
            settled = self._results
            self._results = []
        return settled

    def stop(self) -> None:
        with self._cond:
            self._queue.append(None)
            self._cond.notify()
        self._thread.join(timeout=30)


# -- the loop ------------------------------------------------------------------


@dataclasses.dataclass
class ContinuousResult:
    """What a bounded continuous run did (the unbounded form never
    returns): final state, step count, gate/cutover history, and the
    ledger's own accounting."""

    state: Any
    steps: int
    gates: list[dict[str, Any]]
    cutovers: list[dict[str, Any]]
    recoveries: int
    ledger: dict[str, Any]


def _improves(metric: float, best: float | None, mode: str,
              min_delta: float) -> bool:
    if best is None:
        return True
    if mode == "max":
        return metric >= best - min_delta
    return metric <= best + min_delta


def _advance_bar(best: float | None, metric: float, mode: str) -> float:
    """The new comparison bar after an ACCEPTED candidate: only genuine
    improvement moves it. A candidate merely tolerated by ``min_delta``
    must not lower the bar, or a model regressing by less than
    ``min_delta`` per gate would ratchet it down forever and the gate
    would never catch the slow slide."""
    if best is None:
        return metric
    return max(best, metric) if mode == "max" else min(best, metric)


def run_continuous(
    train_step: Callable[[Any, Any], tuple[Any, Any]],
    state: Any,
    stream: SpanStream,
    *,
    directory: str | Path,
    eval_fn: Callable[[Any], float] | None = None,
    mode: str = "max",
    min_delta: float = 0.0,
    publisher: RegistryFleetPublisher | None = None,
    save_every: int = 10,
    max_recoveries: int = 3,
    recovery_policy: Any = None,
    guard: PreemptionGuard | None = None,
) -> ContinuousResult:
    """Drive the closed loop: train on streaming spans, gate every
    ``stream.eval_every`` steps, cut passing candidates over.

    Each segment is one ``run_preemptible`` call (restore → train →
    checkpoint, with its supervisor absorbing transient faults); the
    eval gate runs between segments, on the just-checkpointed state.
    The gate compares against the last *accepted* candidate's metric:
    a regression (worse than ``min_delta`` under ``mode``) fails the
    gate and the candidate never reaches the registry or the fleet —
    the incumbent keeps serving, which IS the rollback. A candidate
    that passes but trips the canary breaker is rolled back by the
    rollout itself; its metric is then not adopted as the bar.

    Cutovers are **asynchronous**: a passed candidate is handed to a
    single FIFO background thread (:class:`_CutoverWorker`) and the
    next segment starts training immediately — the registry push and
    canary-judged fleet rollout no longer pause the stream. Outcomes
    are settled on the training thread right before the next gate (and
    before returning), so the bar every gate judges against is
    identical to the inline ordering.

    Runs until the stream finishes (``max_steps`` / ``stop_when`` /
    idle with ``stop_on_idle``) or a preemption notice arrives.
    ``mode`` is ``"max"`` (higher is better) or ``"min"``.
    """
    if mode not in ("max", "min"):
        raise ValueError(f"mode must be 'max' or 'min', got {mode!r}")
    from hops_tpu.runtime.resilience import RetryPolicy

    policy = recovery_policy or RetryPolicy(base_delay_s=0.05, max_delay_s=2.0)
    own_guard = guard is None
    guard = guard or PreemptionGuard()
    # A preemption notice must break a __next__ that is blocked waiting
    # for records — chain the guard into the stream's stop predicate so
    # the poll-wait loop sees it at poll cadence.
    user_stop = stream.stop_when
    stream.stop_when = lambda: guard.should_stop() or (
        user_stop() if user_stop is not None else False)
    recoveries0 = _recoveries_now()
    best: float | None = None
    gates: list[dict[str, Any]] = []
    cutovers: list[dict[str, Any]] = []
    worker = _CutoverWorker(publisher) if publisher is not None else None
    done = 0

    def fold_cutovers() -> None:
        """Settle in-flight rollouts and fold their outcomes into the
        bookkeeping (cutover history, metrics, the comparison bar).
        Runs on the training thread right before each gate decision and
        once before returning — every gate judges against a bar that
        reflects all prior cutover outcomes, same as the inline call."""
        nonlocal best
        failure: BaseException | None = None
        for cstep, cmetric, cut, err in worker.drain():
            if err is not None:
                failure = failure or err
                continue
            _m_cutovers.inc(outcome=cut["outcome"])
            flight.record("cutover", step=cstep,
                          version=cut.get("version"),
                          outcome=cut["outcome"])
            cutovers.append({"step": cstep, "metric": cmetric, **cut})
            if cut["outcome"] in ("pushed", "completed"):
                best = _advance_bar(best, cmetric, mode)
            else:
                log.warning(
                    "continuous: cutover of version %s at step %d "
                    "ended %s — the fleet rolled back, the bar "
                    "stays at %.6g",
                    cut.get("version"), cstep, cut["outcome"],
                    best if best is not None else float("nan"))
        if failure is not None:
            raise failure

    try:
        while True:
            prev_done = done
            state, _, done = run_preemptible(
                train_step, state, stream,
                directory=str(directory), save_every=save_every,
                guard=guard, max_recoveries=max_recoveries,
                recovery_policy=policy)
            preempted = guard.should_stop()
            if eval_fn is not None and done > prev_done and not preempted:
                t0 = time.monotonic()
                metric = float(eval_fn(state))
                _m_gate_seconds.observe(time.monotonic() - t0)
                if worker is not None:
                    # The previous segment trained WHILE its cutover
                    # rolled out; settle the outcome now so this gate
                    # judges against the true bar.
                    fold_cutovers()
                passed = _improves(metric, best, mode, min_delta)
                outcome = "pass" if passed else "fail"
                _m_gates.inc(outcome=outcome)
                flight.record("eval_gate", step=done, outcome=outcome,
                              metric=metric, best=best)
                gates.append({"step": done, "metric": metric,
                              "outcome": outcome, "best": best,
                              "latency_s": round(time.monotonic() - t0, 4)})
                if not passed:
                    log.warning(
                        "continuous: eval gate FAILED at step %d (%s=%.6g "
                        "vs best %.6g) — candidate held back, incumbent "
                        "keeps serving", done, mode, metric, best)
                elif worker is not None:
                    # Hand the rollout to the background worker: the
                    # next segment starts training immediately while
                    # the registry push + canary bake run off-thread.
                    worker.submit(state, done, metric)
                else:
                    best = _advance_bar(best, metric, mode)
            if stream.finished or preempted:
                break
            if done == prev_done and not stream.finished:
                # A segment that trained nothing and did not finish is
                # a wedged stream — bail rather than spin forever.
                log.warning("continuous: segment at step %d made no "
                            "progress; stopping", done)
                break
        if worker is not None:
            fold_cutovers()  # the final segment's rollout, if any
    finally:
        if worker is not None:
            worker.stop()
        if own_guard:
            guard.uninstall()
    return ContinuousResult(
        state=state, steps=done, gates=gates, cutovers=cutovers,
        recoveries=int(_recoveries_now() - recoveries0),
        ledger=stream.ledger.verify(),
    )


def _recoveries_now() -> float:
    metric = REGISTRY.get("hops_tpu_run_recoveries_total")
    if metric is None:
        return 0.0
    try:
        return metric.value(loop="preemptible")
    except Exception:  # noqa: BLE001 — label child not created yet
        return 0.0


def collate_column_batch(columns: list[str]) -> Callable[[list], dict]:
    """A convenience collate for dict-valued records: stack the given
    columns into float arrays — ``[{"x": [...], "y": 1}, ...]`` becomes
    ``{"x": (n, d), "y": (n,)}``."""

    def collate(values: list) -> dict:
        return {c: np.asarray([v[c] for v in values], dtype=np.float64)
                for c in columns}

    return collate
