"""``hops_tpu.pipeline`` — the end-to-end platform loop.

PAPER.md's L3→L4 spine (Kafka → experiment → model repo → serving) as
one continuously-running system: a pubsub topic feeds an unbounded
streaming loader source (:class:`~hops_tpu.featurestore.StreamingSource`),
``run_preemptible`` trains on fresh spans under an exactly-once span
ledger, an eval gate scores every candidate, and passing checkpoints
roll into the serving fleet via breaker-judged rollouts with automatic
rollback. See :mod:`hops_tpu.pipeline.continuous`.
"""

from __future__ import annotations

from hops_tpu.pipeline.continuous import (  # noqa: F401
    ContinuousResult,
    RegistryFleetPublisher,
    SpanLedger,
    SpanStream,
    run_continuous,
)

__all__ = [
    "ContinuousResult",
    "RegistryFleetPublisher",
    "SpanLedger",
    "SpanStream",
    "run_continuous",
]
