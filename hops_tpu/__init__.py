"""hops_tpu — a TPU-native ML-platform framework.

A ground-up re-design of the capabilities of the Hopsworks example suite
(``moritzmeister/hops-examples``, see SURVEY.md) for TPU hardware:

- ``hops_tpu.experiment`` — wrapper-function experiment launchers
  (``launch`` / ``mirrored`` / ``collective_all_reduce`` / ``grid_search`` /
  ``differential_evolution``), replacing Spark-executor launchers
  (reference: notebooks/ml/Experiment/*, SURVEY.md §2.3).
- ``hops_tpu.search`` — async parallel-trial driver (maggy-equivalent
  ``lagom``: Searchspace, reporter heartbeats, random search / ASHA,
  early stopping, LOCO ablation; reference: SURVEY.md §2.4).
- ``hops_tpu.runtime`` — slice topology discovery (``devices``), typed
  config, structured logging, run directories, filesystem façade
  (reference: hops.devices / hops.hdfs, SURVEY.md §2.2).
- ``hops_tpu.modelrepo`` — versioned model registry + serving + batch
  inference (reference: hops.model / hops.serving, SURVEY.md §2.5).
- ``hops_tpu.featurestore`` — feature-store layer: feature groups, lazy
  query algebra, time travel, training datasets, validation, tags
  (reference: hsfs, SURVEY.md §2.6).
- ``hops_tpu.jobs`` — jobs/orchestration API + DAG operators
  (reference: jobs-client/, airflow/, SURVEY.md §2.7).
- ``hops_tpu.telemetry`` — metrics registry, Prometheus ``/metrics``
  export, pubsub metric shipping, span timers (reference: the
  Kafka→ELK inference-log / Spark-executor-metrics pipeline,
  SURVEY.md §5).
- ``hops_tpu.parallel`` — meshes, shardings, collectives, ring attention.
- ``hops_tpu.ops`` — Pallas TPU kernels for hot ops.
- ``hops_tpu.models`` — model zoo (MNIST CNN/FFN, ResNet-50, wide&deep).

Distribution is SPMD over ``jax.sharding.Mesh`` with XLA collectives over
ICI/DCN — no Spark, no NCCL, no JVM.
"""

__version__ = "0.1.0"

from hops_tpu.runtime import config, devices, fs, rundir  # noqa: F401

__all__ = [
    "__version__",
    "config",
    "devices",
    "fs",
    "rundir",
]
