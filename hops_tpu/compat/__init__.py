"""`hops`-compatible API shims — run reference notebook code unchanged.

A user of the reference writes ``from hops import experiment, hdfs,
model, serving, kafka, tls, devices, util, hive, elasticsearch`` and
``from maggy import experiment as maggy_experiment`` (SURVEY.md §2.2-2.4).
These shims expose the same module/function names over the TPU-native
implementations, so that code moves with one import change:

    from hops_tpu.compat import experiment, hdfs, model, serving
    experiment.launch(train_fn, name="mnist", metric_key="accuracy")
    hdfs.copy_to_local(hdfs.project_path("Resources/data.csv"))

Semantics notes: "GPUs" become TPU chips (``devices.get_num_gpus``),
"executors" become hosts (``util.num_executors``), HDFS paths are
project-workspace paths, Kafka is the embedded pubsub layer. Each shim
is a thin re-export — the native APIs under ``hops_tpu.*`` remain the
first-class surface.
"""

from hops_tpu.compat import (  # noqa: F401
    beam,
    dataset,
    devices,
    elasticsearch,
    experiment,
    hdfs,
    hive,
    numpy_helper,
    pandas_helper,
    jobs,
    kafka,
    maggy,
    model,
    project,
    serving,
    tensorboard,
    tls,
    util,
)
