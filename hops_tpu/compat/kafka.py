"""`hops.kafka` shim (reference: KafkaPython.ipynb usage, SURVEY.md §2.2)."""

from hops_tpu.messaging.pubsub import (  # noqa: F401
    Consumer,
    Producer,
    create_topic,
    get_broker_endpoints,
    get_schema,
    get_security_protocol,
)
