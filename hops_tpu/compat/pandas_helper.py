"""`hops.pandas_helper` shim (reference surface: ml/pandas/pandas-hdfs.ipynb).

``pandas.read_csv(hdfs.project_path() + "/TourData/census/adult.data",
names=..., sep=...)`` and ``pandas.write_csv("Resources/out.csv", df)``
in the reference route pandas IO through the project filesystem; here
the paths resolve into the workspace tree and all pandas keyword
arguments pass through.
"""

from __future__ import annotations

import pandas as pd

from hops_tpu.runtime import fs


def read_csv(path: str, **kwargs) -> pd.DataFrame:
    return pd.read_csv(fs.resolve(path), **kwargs)


def write_csv(path: str, df: pd.DataFrame, **kwargs) -> str:
    dest = fs.resolve(path)
    dest.parent.mkdir(parents=True, exist_ok=True)
    df.to_csv(dest, index=kwargs.pop("index", False), **kwargs)
    return str(dest)


def read_parquet(path: str, **kwargs) -> pd.DataFrame:
    return pd.read_parquet(fs.resolve(path), **kwargs)


def write_parquet(path: str, df: pd.DataFrame, **kwargs) -> str:
    dest = fs.resolve(path)
    dest.parent.mkdir(parents=True, exist_ok=True)
    df.to_parquet(dest, index=kwargs.pop("index", False), **kwargs)
    return str(dest)
