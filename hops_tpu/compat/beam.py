"""`hops.beam` shim (reference surface: jobs_flink_client.py:45-51).

``beam.create_runner(name, ...)`` / ``beam.start_runner(name)`` manage
a long-lived streaming runner; here they front the TPU build's
streaming-job layer (`hops_tpu.jobs.streaming`).
"""

from hops_tpu.jobs.streaming import create_runner, start_runner  # noqa: F401
