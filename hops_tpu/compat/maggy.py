"""`maggy` shim (SURVEY.md §2.4): Searchspace + lagom + ablation.

Reference usage::

    from maggy import Searchspace, experiment
    sp = Searchspace(kernel=('INTEGER', [2, 8]))
    experiment.lagom(train_fn=..., searchspace=sp, optimizer='randomsearch', ...)

maps to ``from hops_tpu.compat import maggy`` then
``maggy.Searchspace(...)`` / ``maggy.experiment.lagom(...)``.
"""

import types

from hops_tpu.search import AblationStudy, Searchspace  # noqa: F401
from hops_tpu.search.drivers import lagom as _lagom
from hops_tpu.experiment import tensorboard  # noqa: F401

experiment = types.SimpleNamespace(lagom=_lagom)
