"""`hops.project` shim — control-plane connection (SURVEY.md §2.7)."""

from hops_tpu.runtime import config as _config
from hops_tpu.runtime import fs as _fs


def connect(project: str | None = None, host: str | None = None,
            api_key: str | None = None, **_ignored):
    """Reference: REST handshake; here, select/initialize the local
    project workspace."""
    if project:
        _config.configure(project=project)
    return _fs.project_name()
