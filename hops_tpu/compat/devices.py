"""`hops.devices` shim — accelerator discovery (SURVEY.md §2.2).

"GPUs per container" becomes "TPU chips visible to this host".
"""

from hops_tpu.runtime.devices import get_num_chips, get_num_local_chips, topology  # noqa: F401


def get_num_gpus() -> int:
    """Reference name; counts this host's TPU chips."""
    return get_num_local_chips()
