"""`hops.serving` shim — serving lifecycle + inference (SURVEY.md §2.5)."""

from hops_tpu.modelrepo.serving import (  # noqa: F401
    create_or_update,
    delete,
    exists,
    get_all,
    get_kafka_topic,
    get_status,
    make_inference_request,
    start,
    stop,
)
