"""`hops.dataset` shim — dataset staging (jobs_spark_client.py:49-50)."""

from hops_tpu.jobs.dataset import download, extract, upload, upload_workspace  # noqa: F401
