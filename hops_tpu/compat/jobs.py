"""`hops.jobs` shim — jobs REST verbs (SURVEY.md §2.7)."""

from hops_tpu.jobs.api import (  # noqa: F401
    create_job,
    delete_job,
    get_executions,
    get_job,
    get_jobs,
    start_job,
    stop_job,
    wait_for_completion,
)
