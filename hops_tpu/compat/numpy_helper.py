"""`hops.numpy_helper` shim (reference surface: ml/numpy/numpy-hdfs.ipynb).

The reference wraps numpy IO so ``.npy`` files live in the project
filesystem: ``numpy.load("TourData/numpy/C_test.npy")`` and
``numpy.save("Resources/out.npy", arr)`` accept project-relative or
full project paths. Same contract here over the workspace tree; paths
resolve directly (so ``mmap_mode`` and all numpy kwargs work).
"""

from __future__ import annotations

import numpy as np

from hops_tpu.runtime import fs


def load(path: str, **kwargs):
    """np.load from a project-relative (or absolute workspace) path."""
    return np.load(fs.resolve(path), **kwargs)


def save(path: str, arr) -> str:
    """np.save to a project-relative (or absolute workspace) path.

    Returns the path actually written: numpy appends ``.npy`` when the
    input lacks it, and so does the return value."""
    dest = fs.resolve(path)
    dest.parent.mkdir(parents=True, exist_ok=True)
    np.save(dest, arr)
    return str(dest if dest.suffix == ".npy" else dest.with_name(dest.name + ".npy"))


def savez(path: str, *args, **kwargs) -> str:
    dest = fs.resolve(path)
    dest.parent.mkdir(parents=True, exist_ok=True)
    np.savez(dest, *args, **kwargs)
    return str(dest if dest.suffix == ".npz" else dest.with_name(dest.name + ".npz"))
