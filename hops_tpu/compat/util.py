"""`hops.util` shim (SURVEY.md §2.2): cluster-size introspection."""

from hops_tpu.runtime import devices as _devices


def num_executors() -> int:
    """Reference: Spark executor count; here, hosts in the slice."""
    return _devices.num_hosts()


def num_param_servers() -> int:
    """PS has no TPU analog (SURVEY.md §2.9 row 3); always 0."""
    return 0
