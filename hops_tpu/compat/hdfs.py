"""`hops.hdfs` shim (reference surface: SURVEY.md §2.2 hdfs row).

Project-scoped filesystem verbs over the workspace tree; ``hdfs://``
URI arguments are accepted and mapped into the project path.
"""

from hops_tpu.runtime.fs import (  # noqa: F401
    chmod,
    cp,
    copy_to_local,
    dump,
    exists,
    glob,
    load,
    ls,
    lsl,
    mkdir,
    move,
    project_name,
    project_path,
    project_user,
    rename,
    rmr,
    stat,
)
from hops_tpu.runtime.fs import copy_to_workspace as copy_to_hdfs  # noqa: F401
