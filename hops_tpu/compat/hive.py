"""`hops.hive` shim — SQL gateway (reference: PyHive.ipynb:46)."""

from hops_tpu.sql import gateway as _gateway


def setup_hive_connection(feature_store=None):
    """Reference name; returns a DB-API-style connection over the
    feature store's tables."""
    return _gateway.connection(feature_store)
