"""`hops.model` shim — model repository (SURVEY.md §2.5)."""

from hops_tpu.modelrepo.registry import (  # noqa: F401
    Metric,
    export,
    get_best_model,
    get_model,
    list_models,
)
