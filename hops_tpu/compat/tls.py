"""`hops.tls` shim — per-project security material (SURVEY.md §2.2)."""

from hops_tpu.messaging.tls import (  # noqa: F401
    get_ca_chain_location,
    get_client_certificate_location,
    get_client_key_location,
    get_key_store,
    get_key_store_pwd,
    get_trust_store,
    get_trust_store_pwd,
)
