"""`hops.elasticsearch` shim (reference: Elasticsearch-python.ipynb:72)."""

from hops_tpu.messaging.searchindex import get_elasticsearch_config  # noqa: F401
