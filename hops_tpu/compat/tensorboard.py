"""`hops.tensorboard` shim — per-run logdir contract (SURVEY.md §2.3)."""

from hops_tpu.experiment.tensorboard import flush, logdir, profile, scalar  # noqa: F401
