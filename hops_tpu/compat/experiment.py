"""`hops.experiment` shim (SURVEY.md §2.3) — identical call surface."""

from hops_tpu.experiment import (  # noqa: F401
    collective_all_reduce,
    differential_evolution,
    grid_search,
    lagom,
    launch,
    mirrored,
    parameter_server,
)
