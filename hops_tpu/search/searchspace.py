"""Searchspace: typed hyperparameter domains.

Reference surface: ``Searchspace(kernel=('INTEGER', [2, 8]))`` /
``.add('dropout', ('DOUBLE', [0.01, 0.99]))`` with case-insensitive type
names (maggy-fashion-mnist-example.ipynb:124-130, SURVEY.md §2.4).
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Iterator

_TYPES = ("INTEGER", "DOUBLE", "DISCRETE", "CATEGORICAL")


class Searchspace:
    def __init__(self, **params: tuple[str, list[Any]]):
        self._params: dict[str, tuple[str, list[Any]]] = {}
        for name, spec in params.items():
            self.add(name, spec)

    def add(self, name: str, spec: tuple[str, list[Any]]) -> "Searchspace":
        kind, domain = spec
        kind = kind.upper()
        if kind not in _TYPES:
            raise ValueError(f"unknown searchspace type {kind!r}; expected one of {_TYPES}")
        if kind in ("INTEGER", "DOUBLE"):
            if len(domain) != 2 or domain[0] > domain[1]:
                raise ValueError(f"{name}: {kind} needs [min, max], got {domain}")
        elif not domain:
            raise ValueError(f"{name}: empty domain")
        self._params[name] = (kind, list(domain))
        return self

    def names(self) -> list[str]:
        return list(self._params)

    def items(self):
        return self._params.items()

    def __contains__(self, name: str) -> bool:
        return name in self._params

    def __repr__(self) -> str:
        inner = ", ".join(f"{k}={v[0]}{v[1]}" for k, v in self._params.items())
        return f"Searchspace({inner})"

    def sample(self, rng: random.Random) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for name, (kind, domain) in self._params.items():
            if kind == "INTEGER":
                out[name] = rng.randint(int(domain[0]), int(domain[1]))
            elif kind == "DOUBLE":
                out[name] = rng.uniform(float(domain[0]), float(domain[1]))
            else:  # DISCRETE / CATEGORICAL
                out[name] = rng.choice(domain)
        return out

    def grid(self, doubles_per_axis: int = 5) -> Iterator[dict[str, Any]]:
        """Cartesian grid; continuous axes discretized."""
        axes: list[list[Any]] = []
        for kind, domain in self._params.values():
            if kind == "INTEGER":
                axes.append(list(range(int(domain[0]), int(domain[1]) + 1)))
            elif kind == "DOUBLE":
                lo, hi = float(domain[0]), float(domain[1])
                n = doubles_per_axis
                axes.append([lo + (hi - lo) * i / (n - 1) for i in range(n)])
            else:
                axes.append(list(domain))
        for combo in itertools.product(*axes):
            yield dict(zip(self._params, combo))

    def clip(self, params: dict[str, Any]) -> dict[str, Any]:
        """Project arbitrary values back into the domain (used by
        differential evolution's mutation step)."""
        out = dict(params)
        for name, (kind, domain) in self._params.items():
            v = out.get(name)
            if kind == "INTEGER":
                out[name] = int(min(max(round(v), domain[0]), domain[1]))
            elif kind == "DOUBLE":
                out[name] = float(min(max(v, domain[0]), domain[1]))
            elif v not in domain:
                out[name] = min(domain, key=lambda d: abs(hash(d) - hash(v)))
        return out
