"""The async trial driver and its public entry points.

Architecture (reproducing SURVEY.md §3.3 TPU-natively): a driver-side
optimizer loop + RPC heartbeat server; executor threads run trials on
**disjoint sub-slices** — the visible chips partition into groups of
``devices_per_trial`` (1 chip, 2 chips, 2x2, ...), each concurrent
trial leases one group from a pool, and inside the trial
``parallel.mesh.make_mesh``/``local_mesh`` default to that group (a
thread-local ``device_scope``), so a trial can pjit over its own
sub-mesh without seeing its neighbors' chips (SURVEY.md §7 hard part
#2). Reporters stream metrics back at ``hb_interval``; an early stopper
flags underperformers, which die cooperatively at their next step
boundary. No barrier between trials — completions feed the optimizer as
they land (lagom semantics).

Entry points: :func:`lagom` (maggy, SURVEY.md §2.4), :func:`grid_search`
and :func:`differential_evolution` (``hops.experiment``, SURVEY.md §2.3).
"""

from __future__ import annotations

import concurrent.futures as cf
import dataclasses
import inspect
import json
import threading
import time
from pathlib import Path
from typing import Any, Callable

import jax

from hops_tpu.experiment import registry
from hops_tpu.messaging.rpc import RpcServer
from hops_tpu.runtime import rundir
from hops_tpu.runtime.logging import get_logger, scalarize
from hops_tpu.search.ablation import AblationStudy, LOCOAblator
from hops_tpu.search.earlystop import MedianEarlyStopper, NoEarlyStop
from hops_tpu.search.optimizers import (
    DifferentialEvolution,
    GridSearch,
    Optimizer,
    TrialResult,
    make_optimizer,
)
from hops_tpu.search.reporter import Reporter, TrialStopped
from hops_tpu.search.searchspace import Searchspace
from hops_tpu.telemetry.metrics import REGISTRY

log = get_logger(__name__)


class _TrialDir:
    """Shim with the RunDir interface rundir.activate() needs, rooted
    inside the parent experiment's directory."""

    def __init__(self, path: Path):
        path.mkdir(parents=True, exist_ok=True)
        self.logdir = str(path)


class TrialDriver:
    def __init__(
        self,
        train_fn: Callable[..., Any],
        optimizer: Optimizer,
        name: str = "search",
        kind: str = "lagom",
        direction: str = "max",
        optimization_key: str | None = None,
        hb_interval: float = 1.0,
        es_interval: float = 1.0,
        early_stopper: Any = None,
        max_parallel: int | None = None,
        devices_per_trial: int = 1,
        use_rpc: bool = True,
        retry_policy: Any = None,
    ):
        self.train_fn = train_fn
        self.optimizer = optimizer
        # Transient trial failures (device hiccup, flaky I/O) retry
        # under the policy before the trial is marked failed; an
        # early-stop signal is never a failure, so never retried.
        self.retry_policy = (
            None if retry_policy is None
            else dataclasses.replace(
                retry_policy,
                no_retry_on=tuple(retry_policy.no_retry_on) + (TrialStopped,),
            )
        )
        self.name = name
        self.kind = kind
        self.direction = direction.lower()
        self.optimization_key = optimization_key
        self.hb_interval = hb_interval
        self.es_interval = es_interval
        self.early_stopper = early_stopper or NoEarlyStop()
        self.devices = jax.local_devices()
        if devices_per_trial < 1 or devices_per_trial > len(self.devices):
            raise ValueError(
                f"devices_per_trial={devices_per_trial} with "
                f"{len(self.devices)} visible devices"
            )
        # Disjoint contiguous groups: host-major device order keeps a
        # group's chips ICI-adjacent, so a trial's collectives stay
        # inside its sub-slice.
        devs = sorted(self.devices, key=lambda d: (d.process_index, d.id))
        n_groups = len(devs) // devices_per_trial
        self.device_groups = [
            tuple(devs[i * devices_per_trial : (i + 1) * devices_per_trial])
            for i in range(n_groups)
        ]
        self.max_parallel = min(max_parallel or n_groups, n_groups)
        self.use_rpc = use_rpc
        self._wants_reporter = "reporter" in inspect.signature(train_fn).parameters
        self._reporters: dict[str, Reporter] = {}
        self._finished_finals: list[float] = []
        self._lock = threading.Lock()
        # Trial lifecycle counters: started / finished / early_stopped /
        # failed, per driver kind (lagom, grid_search, ...). rate() on
        # "finished" is search throughput.
        self._m_trials = REGISTRY.counter(
            "hops_tpu_search_trials_total",
            "Search trials by lifecycle event",
            labels=("kind", "event"),
        )

    # -- heartbeat handler (driver side of the RPC channel) -------------------

    def _on_heartbeat(self, trial_id: str, step: int, metric: float | None) -> dict:
        with self._lock:
            rep = self._reporters.get(trial_id)
            stop = rep is not None and rep._stop.is_set()
        return {"stop": stop}

    # -- trial execution (executor-thread side) --------------------------------

    def _run_trial(
        self,
        trial_id: str,
        params: dict[str, Any],
        group: tuple[Any, ...],
        parent_dir: Path,
        rpc_address: tuple[str, int] | None,
    ) -> TrialResult:
        self._m_trials.inc(kind=self.kind, event="started")
        reporter = Reporter(trial_id, rpc_address, self.hb_interval)
        with self._lock:
            self._reporters[trial_id] = reporter
        visible = {k: v for k, v in params.items() if not k.startswith("_")}
        kwargs = dict(visible)
        if self._wants_reporter:
            kwargs["reporter"] = reporter
        trial_dir = _TrialDir(parent_dir / trial_id)
        stopped = False
        error: str | None = None
        metric: float | None = None
        try:
            from hops_tpu.parallel import mesh as mesh_lib
            from hops_tpu.runtime import faultinject

            def _attempt():
                faultinject.fire("search.trial")  # chaos: flaky trial
                with (
                    jax.default_device(group[0]),
                    mesh_lib.device_scope(group),
                    rundir.activate(trial_dir),
                ):
                    return self.train_fn(**kwargs)

            if self.retry_policy is None:
                result = _attempt()
            else:
                result = self.retry_policy.call(_attempt, op="search.trial")
            metric = self._extract_metric(result)
        except TrialStopped:
            stopped = True
            metric = reporter.latest
        except Exception as e:  # noqa: BLE001 — one bad trial must not kill the search
            error = f"{type(e).__name__}: {e}"
            log.warning("trial %s failed: %s", trial_id, error)
        finally:
            reporter.finalize(metric)
            from hops_tpu.experiment import tensorboard as _tb

            _tb.close(trial_dir.logdir)
        self._m_trials.inc(
            kind=self.kind,
            event=(
                "early_stopped" if stopped
                else "failed" if error is not None
                else "finished"
            ),
        )
        (Path(trial_dir.logdir) / "trial.json").write_text(
            json.dumps(
                {
                    "trial_id": trial_id,
                    "params": {k: scalarize(v) for k, v in visible.items()},
                    "metric": metric,
                    "stopped_early": stopped,
                    "error": error,
                    "history": reporter.history,
                },
                default=str,
            )
        )
        return TrialResult(
            trial_id, params, metric, stopped_early=stopped, meta={**params, "error": error}
        )

    def _extract_metric(self, result: Any) -> float | None:
        if isinstance(result, dict):
            if self.optimization_key is not None:
                v = result.get(self.optimization_key)
            elif len(result) == 1:
                v = next(iter(result.values()))
            else:
                v = result.get("metric")
            return None if v is None else float(v)
        return None if result is None else float(result)

    # -- the async driver loop -------------------------------------------------

    def run(self) -> tuple[str, dict[str, Any]]:
        run = rundir.new_run(name=self.name)
        parent_dir = Path(run.logdir)
        registry.register(
            {"run_id": run.run_id, "name": self.name, "kind": self.kind, "status": "RUNNING"}
        )
        server = None
        rpc_address = None
        if self.use_rpc:
            server = RpcServer()
            server.register("heartbeat", self._on_heartbeat)
            server.start()
            rpc_address = server.address

        start = time.time()
        results: list[TrialResult] = []
        trial_seq = 0
        pending: dict[cf.Future, str] = {}
        free_groups = list(self.device_groups)
        leased: dict[str, tuple[Any, ...]] = {}
        self._last_sweep = time.monotonic()
        try:
            with cf.ThreadPoolExecutor(max_workers=self.max_parallel) as pool:
                while True:
                    # Issue every trial the optimizer can produce right
                    # now, each leasing a free device group.
                    while len(pending) < self.max_parallel and free_groups:
                        params = self.optimizer.ask()
                        if params is None:
                            break
                        tid = f"trial_{trial_seq:04d}"
                        trial_seq += 1
                        group = free_groups.pop()
                        leased[tid] = group
                        fut = pool.submit(
                            self._run_trial, tid, params, group, parent_dir, rpc_address
                        )
                        pending[fut] = tid
                    if not pending:
                        if self.optimizer.finished():
                            break
                        time.sleep(0.005)
                        continue
                    done, _ = cf.wait(
                        pending, timeout=self.es_interval, return_when=cf.FIRST_COMPLETED
                    )
                    for fut in done:
                        tid = pending.pop(fut)
                        free_groups.append(leased.pop(tid))
                        result = fut.result()
                        results.append(result)
                        with self._lock:
                            self._reporters.pop(tid, None)
                            if result.metric is not None and not result.stopped_early:
                                self._finished_finals.append(result.metric)
                        self.optimizer.tell(result)
                    self._early_stop_sweep()
        finally:
            if server is not None:
                server.stop()

        scored = [r for r in results if r.metric is not None]
        best = None
        if scored:
            pick = max if self.direction == "max" else min
            best = pick(scored, key=lambda r: r.metric)
        summary = {
            "best_id": best.trial_id if best else None,
            "best_config": (
                {k: v for k, v in best.params.items() if not k.startswith("_")} if best else None
            ),
            "best_metric": best.metric if best else None,
            "num_trials": len(results),
            "early_stopped": sum(r.stopped_early for r in results),
            # direction + per-trial params travel with the summary so
            # downstream tooling (hops_tpu.plotting.plot_trials /
            # collect) can orient the best-so-far envelope and plot
            # metric-vs-param without re-reading trial dirs.
            "direction": self.direction,
            "trials": {
                r.trial_id: {
                    "metric": r.metric,
                    "stopped_early": r.stopped_early,
                    "params": {
                        k: v for k, v in r.params.items()
                        if not k.startswith("_")
                    },
                }
                for r in results
            },
        }
        (parent_dir / "result.json").write_text(json.dumps(summary, indent=2, default=str))
        final_path = run.finalize()
        registry.register(
            {
                "run_id": run.run_id,
                "name": self.name,
                "kind": self.kind,
                "status": "FINISHED",
                "metrics": {"metric": best.metric if best else None},
                "best_config": summary["best_config"],
                "duration_s": time.time() - start,
                "path": final_path,
            }
        )
        return final_path, summary

    def _early_stop_sweep(self) -> None:
        if time.monotonic() - self._last_sweep < self.es_interval:
            return
        self._last_sweep = time.monotonic()
        with self._lock:
            finals = list(self._finished_finals)
            for rep in self._reporters.values():
                if self.early_stopper.should_stop(rep.latest, finals):
                    rep.request_stop()


# -- public entry points ------------------------------------------------------


def lagom(
    train_fn: Callable[..., Any] | None = None,
    searchspace: Searchspace | None = None,
    optimizer: str | Optimizer = "randomsearch",
    direction: str = "max",
    num_trials: int = 10,
    name: str = "lagom",
    hb_interval: float = 1.0,
    es_interval: float = 1.0,
    es_min: int = 5,
    experiment_type: str = "optimization",
    ablation_study: AblationStudy | None = None,
    ablator: str = "loco",
    optimization_key: str | None = None,
    max_parallel: int | None = None,
    devices_per_trial: int = 1,
    retry_policy: Any = None,
) -> dict[str, Any]:
    """Async parallel trials (reference: ``maggy.experiment.lagom``,
    maggy-fashion-mnist-example.ipynb:318-327).

    ``devices_per_trial`` places each trial on its own disjoint
    sub-slice of that many chips; inside the trial,
    ``parallel.mesh.make_mesh()`` builds over just that group.
    ``retry_policy`` (a ``runtime.resilience.RetryPolicy``) retries a
    trial that raised before marking it failed."""
    if experiment_type == "ablation":
        if ablation_study is None:
            raise ValueError("experiment_type='ablation' requires ablation_study=")
        if ablator.lower() != "loco":
            raise ValueError(f"unknown ablator {ablator!r}")
        opt = GridSearch.from_trials(LOCOAblator(ablation_study).trials(), direction)
    else:
        if searchspace is None:
            raise ValueError("optimization experiments require searchspace=")
        opt = make_optimizer(optimizer, searchspace, num_trials, direction)
    driver = TrialDriver(
        train_fn,
        opt,
        name=name,
        kind="lagom" if experiment_type == "optimization" else "ablation",
        direction=direction,
        optimization_key=optimization_key,
        hb_interval=hb_interval,
        es_interval=es_interval,
        early_stopper=MedianEarlyStopper(direction, es_min),
        max_parallel=max_parallel,
        devices_per_trial=devices_per_trial,
        retry_policy=retry_policy,
    )
    path, summary = driver.run()
    summary["path"] = path
    return summary


def grid_search(
    train_fn: Callable[..., Any],
    args_dict: dict[str, list[Any]],
    direction: str = "max",
    optimization_key: str | None = None,
    name: str = "grid_search",
    max_parallel: int | None = None,
    devices_per_trial: int = 1,
    retry_policy: Any = None,
) -> tuple[str, dict[str, Any]]:
    """Exhaustive sweep (reference: ``experiment.grid_search``,
    grid_search_fashion_mnist.ipynb:311 — args_dict keys are wrapper
    kwargs, values are candidate lists)."""
    driver = TrialDriver(
        train_fn,
        GridSearch(args_dict, direction),
        name=name,
        kind="grid_search",
        direction=direction,
        optimization_key=optimization_key,
        max_parallel=max_parallel,
        devices_per_trial=devices_per_trial,
        retry_policy=retry_policy,
    )
    return driver.run()


def differential_evolution(
    train_fn: Callable[..., Any],
    searchdict: dict[str, list[Any]] | Searchspace,
    generations: int = 4,
    population: int = 5,
    direction: str = "max",
    optimization_key: str | None = None,
    local_logdir: bool = False,  # accepted for reference parity; trials live in the run dir
    name: str = "differential_evolution",
    max_parallel: int | None = None,
    devices_per_trial: int = 1,
    retry_policy: Any = None,
) -> tuple[str, dict[str, Any]]:
    """Genetic search (reference: ``experiment.differential_evolution``,
    evolutionary_search_mnist.ipynb:267, generations/population semantics
    from Parallel_Experiments/PyTorch/differential_evolution/mnist.ipynb:230).

    ``searchdict`` may be a ``{"lr": [lo, hi]}`` bounds dict (numeric
    axes become DOUBLE ranges) or a full :class:`Searchspace`."""
    if isinstance(searchdict, Searchspace):
        space = searchdict
    else:
        space = Searchspace()
        for k, bounds in searchdict.items():
            if all(isinstance(b, (int, float)) for b in bounds) and len(bounds) == 2:
                kind = "INTEGER" if all(isinstance(b, int) for b in bounds) else "DOUBLE"
                space.add(k, (kind, list(bounds)))
            else:
                space.add(k, ("DISCRETE", list(bounds)))
    driver = TrialDriver(
        train_fn,
        DifferentialEvolution(space, generations, population, direction),
        name=name,
        kind="differential_evolution",
        direction=direction,
        optimization_key=optimization_key,
        max_parallel=max_parallel,
        devices_per_trial=devices_per_trial,
        retry_policy=retry_policy,
    )
    return driver.run()
