"""Async parallel-trial search — the maggy equivalent (SURVEY.md §2.4).

Surface mirrored from the reference:

- :class:`Searchspace` with INTEGER/DOUBLE/DISCRETE/CATEGORICAL types
  (maggy-fashion-mnist-example.ipynb:124-130)
- trial functions take hyperparameters as kwargs plus ``reporter`` and
  return a scalar metric (or dict)
- :func:`~hops_tpu.search.drivers.lagom` async driver: optimizer loop +
  heartbeat RPC + early stopping + LOCO ablation
- ``grid_search`` / ``differential_evolution`` drivers backing
  ``hops_tpu.experiment``'s entry points (SURVEY.md §2.3)

TPU-native twist: trials are scheduled onto individual chips of the
slice (``jax.default_device`` pinning per executor thread) instead of
Spark executors — task parallelism over the mesh (SURVEY.md §2.9 row 4).
"""

from hops_tpu.search.ablation import AblationStudy  # noqa: F401
from hops_tpu.search.drivers import (  # noqa: F401
    differential_evolution,
    grid_search,
    lagom,
)
from hops_tpu.search.earlystop import MedianEarlyStopper  # noqa: F401
from hops_tpu.search.optimizers import ASHA, DifferentialEvolution, GridSearch, RandomSearch  # noqa: F401
from hops_tpu.search.reporter import Reporter, TrialStopped  # noqa: F401
from hops_tpu.search.searchspace import Searchspace  # noqa: F401
