"""Trial optimizers: ask/tell strategies behind the async driver.

Covers the reference's optimizer set (SURVEY.md §2.3-2.4):
``randomsearch`` and ``asha`` (maggy's lagom optimizers), exhaustive
grid (``experiment.grid_search``), and differential evolution
(``experiment.differential_evolution``). All are ask/tell and
non-blocking: ``ask()`` returns the next trial config or ``None`` when
nothing can be issued *right now* (the driver retries as results come
in), and ``finished()`` says the whole search is exhausted — that is
what makes the lagom loop asynchronous (no generation barrier except
where the algorithm itself demands one).
"""

from __future__ import annotations

import itertools
import random
from dataclasses import dataclass, field
from typing import Any, Iterator

from hops_tpu.search.searchspace import Searchspace


@dataclass
class TrialResult:
    trial_id: str
    params: dict[str, Any]
    metric: float | None
    stopped_early: bool = False
    meta: dict[str, Any] = field(default_factory=dict)


class Optimizer:
    direction: str = "max"

    def better(self, a: float, b: float) -> bool:
        return a > b if self.direction == "max" else a < b

    def ask(self) -> dict[str, Any] | None:
        raise NotImplementedError

    def tell(self, result: TrialResult) -> None:
        raise NotImplementedError

    def finished(self) -> bool:
        raise NotImplementedError


class RandomSearch(Optimizer):
    """maggy ``optimizer='randomsearch'``."""

    def __init__(self, space: Searchspace, num_trials: int, direction: str = "max", seed: int = 0):
        self.space = space
        self.num_trials = num_trials
        self.direction = direction.lower()
        self._rng = random.Random(seed)
        self._asked = 0
        self._told = 0

    def ask(self) -> dict[str, Any] | None:
        if self._asked >= self.num_trials:
            return None
        self._asked += 1
        return self.space.sample(self._rng)

    def tell(self, result: TrialResult) -> None:
        self._told += 1

    def finished(self) -> bool:
        return self._told >= self.num_trials


class GridSearch(Optimizer):
    """``experiment.grid_search``: cartesian product of an args dict
    (grid_search_fashion_mnist.ipynb cell 6 — keys are wrapper kwargs,
    values are lists)."""

    def __init__(self, args_dict: dict[str, list[Any]], direction: str = "max"):
        self.direction = direction.lower()
        keys = list(args_dict)
        self._combos: Iterator[dict[str, Any]] = (
            dict(zip(keys, combo)) for combo in itertools.product(*args_dict.values())
        )
        self.total = 1
        for v in args_dict.values():
            self.total *= len(v)
        self._told = 0

    @classmethod
    def from_trials(cls, trials: list[dict[str, Any]], direction: str = "max") -> "GridSearch":
        """Sequentially issue a precomputed trial list (used by the LOCO
        ablator)."""
        opt = cls.__new__(cls)
        opt.direction = direction.lower()
        opt._combos = iter(trials)
        opt.total = len(trials)
        opt._told = 0
        return opt

    def ask(self) -> dict[str, Any] | None:
        return next(self._combos, None)

    def tell(self, result: TrialResult) -> None:
        self._told += 1

    def finished(self) -> bool:
        return self._told >= self.total


class DifferentialEvolution(Optimizer):
    """``experiment.differential_evolution`` (evolutionary_search_
    mnist.ipynb:267): DE/rand/1/bin over bounded INTEGER/DOUBLE axes;
    categorical axes crossover only. Generations are inherent barriers:
    ``ask()`` returns None while a generation is in flight."""

    def __init__(
        self,
        space: Searchspace,
        generations: int = 4,
        population: int = 5,
        direction: str = "max",
        mutation: float = 0.8,
        crossover: float = 0.7,
        seed: int = 0,
    ):
        if population < 4:
            raise ValueError(f"DE/rand/1 needs population >= 4, got {population}")
        self.space = space
        self.generations = generations
        self.population = population
        self.direction = direction.lower()
        self.mutation = mutation
        self.crossover = crossover
        self._rng = random.Random(seed)
        self._gen = 0
        self._pop: list[dict[str, Any]] = [space.sample(self._rng) for _ in range(population)]
        self._fitness: list[float | None] = [None] * population
        self._pending: list[tuple[int, dict[str, Any]]] = list(enumerate(self._pop))
        self._in_flight: dict[int, dict[str, Any]] = {}
        self._candidates: dict[int, dict[str, Any]] = {}

    def ask(self) -> dict[str, Any] | None:
        if not self._pending:
            return None
        idx, params = self._pending.pop(0)
        self._in_flight[idx] = params
        return {**params, "_de_idx": idx}

    def tell(self, result: TrialResult) -> None:
        idx = result.meta.get("_de_idx", result.params.get("_de_idx"))
        params = self._in_flight.pop(idx)
        metric = result.metric
        prev = self._fitness[idx]
        if metric is not None and (prev is None or self.better(metric, prev)):
            self._fitness[idx] = metric
            self._pop[idx] = params
        if not self._pending and not self._in_flight:
            self._next_generation()

    def _next_generation(self) -> None:
        self._gen += 1
        if self.finished():
            return
        names = self.space.names()
        for i in range(self.population):
            a, b, c = self._rng.sample([j for j in range(self.population) if j != i], 3)
            trial: dict[str, Any] = {}
            for name in names:
                kind, _ = dict(self.space.items())[name]
                target = self._pop[i][name]
                if self._rng.random() < self.crossover:
                    if kind in ("INTEGER", "DOUBLE"):
                        trial[name] = self._pop[a][name] + self.mutation * (
                            self._pop[b][name] - self._pop[c][name]
                        )
                    else:
                        trial[name] = self._rng.choice(
                            [self._pop[a][name], self._pop[b][name], self._pop[c][name]]
                        )
                else:
                    trial[name] = target
            self._pending.append((i, self.space.clip(trial)))

    def finished(self) -> bool:
        return self._gen >= self.generations and not self._pending and not self._in_flight


class ASHA(Optimizer):
    """Asynchronous Successive Halving (the BASELINE.json "Maggy ASHA"
    config): rungs of budgets ``min_budget * eta^r``; a trial finishing
    rung r is promoted to rung r+1 iff it is in the top 1/eta of that
    rung's results so far — fully async, no synchronized halving rounds.
    Trial configs carry a ``budget`` kwarg for the train fn."""

    def __init__(
        self,
        space: Searchspace,
        num_trials: int = 20,
        min_budget: int = 1,
        eta: int = 3,
        max_rungs: int = 4,
        direction: str = "max",
        seed: int = 0,
    ):
        self.space = space
        self.num_trials = num_trials
        self.min_budget = min_budget
        self.eta = eta
        self.max_rungs = max_rungs
        self.direction = direction.lower()
        self._rng = random.Random(seed)
        self._asked_base = 0
        self._done = 0
        # rung -> list of (metric, params)
        self._rungs: dict[int, list[tuple[float, dict[str, Any]]]] = {}
        self._promotable: list[tuple[int, dict[str, Any]]] = []
        self._promoted: dict[int, int] = {}  # rung -> count promoted out

    def budget(self, rung: int) -> int:
        return self.min_budget * self.eta**rung

    def ask(self) -> dict[str, Any] | None:
        if self._promotable:
            rung, params = self._promotable.pop(0)
            return {**params, "budget": self.budget(rung), "_rung": rung}
        if self._asked_base < self.num_trials:
            self._asked_base += 1
            return {
                **self.space.sample(self._rng),
                "budget": self.budget(0),
                "_rung": 0,
            }
        return None

    def tell(self, result: TrialResult) -> None:
        self._done += 1
        rung = result.meta.get("_rung", result.params.get("_rung", 0))
        if result.metric is None:
            return
        params = {
            k: v for k, v in result.params.items() if k not in ("budget", "_rung")
        }
        entries = self._rungs.setdefault(rung, [])
        entries.append((result.metric, params))
        if rung + 1 >= self.max_rungs:
            return
        # Promote while the rung's top-1/eta has grown past what we already
        # promoted (the async rule: never wait for the rung to fill).
        entries.sort(key=lambda t: t[0], reverse=self.direction == "max")
        want = len(entries) // self.eta
        have = self._promoted.get(rung, 0)
        for i in range(have, want):
            self._promotable.append((rung + 1, entries[i][1]))
        self._promoted[rung] = max(have, want)

    def finished(self) -> bool:
        return (
            self._asked_base >= self.num_trials
            and not self._promotable
            and self._done >= self.num_trials + sum(self._promoted.values())
        )


def make_optimizer(
    name_or_opt: Any, space: Searchspace | None, num_trials: int, direction: str
) -> Optimizer:
    if isinstance(name_or_opt, Optimizer):
        return name_or_opt
    name = str(name_or_opt).lower()
    if name == "randomsearch":
        return RandomSearch(space, num_trials, direction)
    if name == "asha":
        return ASHA(space, num_trials, direction=direction)
    raise ValueError(f"unknown optimizer {name_or_opt!r} (expected 'randomsearch', 'asha', or an Optimizer)")
