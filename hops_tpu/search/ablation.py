"""Ablation studies: LOCO (leave-one-component-out).

Reference surface (maggy-ablation-titanic-example.ipynb:135+, SURVEY.md
§2.4): an :class:`AblationStudy` names a training dataset and collects
included features / model layers / layer groups plus a base-model
generator; the ``loco`` ablator expands it into one trial per ablated
component (plus the un-ablated base trial).

Trial contract here: the train fn is called as
``train_fn(ablated_feature=..., ablated_layer=..., reporter=...)`` with
``None`` meaning "nothing ablated"; generators registered on the study
are available to the fn via the study object itself.
"""

from __future__ import annotations

from typing import Any, Callable


class _Features:
    def __init__(self) -> None:
        self.included: list[str] = []

    def include(self, *names: str | list[str]) -> None:
        for n in names:
            if isinstance(n, (list, tuple)):
                self.included.extend(n)
            else:
                self.included.append(n)

    def exclude(self, *names: str) -> None:
        for n in names:
            if n in self.included:
                self.included.remove(n)

    def list_all(self) -> list[str]:
        return list(self.included)


class _Layers:
    def __init__(self) -> None:
        self.included: list[str] = []
        self.groups: list[tuple[str, ...]] = []
        self._prefixes: list[str] = []

    def include(self, *names: str | list[str]) -> None:
        for n in names:
            if isinstance(n, (list, tuple)):
                self.included.extend(n)
            else:
                self.included.append(n)

    def include_groups(self, *groups: list[str], prefix: str | None = None) -> None:
        """A group ablates together; ``prefix=`` groups all *included*
        layers whose name starts with it (reference:
        include_groups(prefix='conv')). Prefixes are expanded against
        the names registered via :meth:`include` when trials are
        generated."""
        for g in groups:
            self.groups.append(tuple(g))
        if prefix is not None:
            self._prefixes.append(prefix)


class _ModelSpec:
    def __init__(self) -> None:
        self.layers = _Layers()
        self._base_model_generator: Callable[..., Any] | None = None

    def set_base_model_generator(self, fn: Callable[..., Any]) -> None:
        self._base_model_generator = fn

    @property
    def base_model_generator(self) -> Callable[..., Any] | None:
        return self._base_model_generator


class AblationStudy:
    def __init__(
        self,
        training_dataset_name: str,
        training_dataset_version: int = 1,
        label_name: str | None = None,
    ):
        self.training_dataset_name = training_dataset_name
        self.training_dataset_version = training_dataset_version
        self.label_name = label_name
        self.features = _Features()
        self.model = _ModelSpec()
        self._dataset_generator: Callable[..., Any] | None = None

    def set_dataset_generator(self, fn: Callable[..., Any]) -> None:
        self._dataset_generator = fn

    @property
    def dataset_generator(self) -> Callable[..., Any] | None:
        return self._dataset_generator


class LOCOAblator:
    """Expand a study into leave-one-out trial configs (LOCO semantics:
    maggy-ablation-titanic-example.ipynb:434)."""

    def __init__(self, study: AblationStudy):
        self.study = study

    def trials(self) -> list[dict[str, Any]]:
        layers = self.study.model.layers
        out: list[dict[str, Any]] = [{"ablated_feature": None, "ablated_layer": None}]
        for feat in self.study.features.included:
            out.append({"ablated_feature": feat, "ablated_layer": None})
        for layer in layers.included:
            out.append({"ablated_feature": None, "ablated_layer": layer})
        for group in layers.groups:
            out.append({"ablated_feature": None, "ablated_layer": list(group)})
        for prefix in layers._prefixes:
            matches = [n for n in layers.included if n.startswith(prefix)]
            if not matches:
                raise ValueError(
                    f"include_groups(prefix={prefix!r}) matched no included layer; "
                    "register layer names via model.layers.include(...) first"
                )
            out.append({"ablated_feature": None, "ablated_layer": matches})
        return out
