"""Early-stopping policies for the async trial driver.

Reference knobs: ``es_interval`` (check period), ``es_min`` (minimum
finished trials before stopping kicks in) — maggy-fashion-mnist-
example.ipynb:307-318, SURVEY.md §2.4. Policy: median rule — a running
trial whose latest metric is worse than the median of completed trials'
final metrics gets stopped.
"""

from __future__ import annotations

import statistics


class MedianEarlyStopper:
    def __init__(self, direction: str = "max", es_min: int = 5):
        self.direction = direction.lower()
        self.es_min = es_min

    def should_stop(
        self, running_latest: float | None, finished_finals: list[float]
    ) -> bool:
        if running_latest is None or len(finished_finals) < self.es_min:
            return False
        med = statistics.median(finished_finals)
        if self.direction == "max":
            return running_latest < med
        return running_latest > med


class NoEarlyStop:
    def should_stop(self, running_latest, finished_finals) -> bool:  # noqa: ARG002
        return False
