"""Trial reporter: live metric stream + cooperative cancellation.

Reference contract (SURVEY.md §2.4): the trial function receives a
``reporter``; ``reporter.broadcast(metric=...)`` streams the current
metric to the driver at heartbeat granularity, and the driver's early
stopper can kill the trial mid-flight. Spark killed the executor task;
on TPU a jitted loop can't be killed externally, so cancellation is
cooperative: the stop flag raises :class:`TrialStopped` inside the next
``broadcast``/``check`` call at a step boundary (SURVEY.md §7 hard
part #3).
"""

from __future__ import annotations

import threading
import time
from typing import Any

from hops_tpu.messaging.rpc import RpcClient


class TrialStopped(Exception):
    """Raised inside a trial when the driver early-stops it."""


class Reporter:
    def __init__(
        self,
        trial_id: str,
        rpc_address: tuple[str, int] | None = None,
        hb_interval: float = 1.0,
        log_fn=print,
    ):
        self.trial_id = trial_id
        self.hb_interval = hb_interval
        self._log_fn = log_fn
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._latest: float | None = None
        self._step = 0
        self.history: list[tuple[int, float]] = []
        self._client = RpcClient(rpc_address) if rpc_address else None
        self._last_hb = 0.0

    # -- trial-side API (reference: reporter.broadcast / reporter.log) -------

    def broadcast(self, metric: float | None = None, step: int | None = None) -> None:
        """Stream the current metric; raises TrialStopped if the driver
        flagged this trial. Call once per step/epoch boundary."""
        with self._lock:
            if metric is not None:
                self._step = step if step is not None else self._step + 1
                self._latest = float(metric)
                self.history.append((self._step, self._latest))
        self._heartbeat(force=False)
        self.check()

    def log(self, msg: str) -> None:
        self._log_fn(f"[{self.trial_id}] {msg}")

    def check(self) -> None:
        if self._stop.is_set():
            raise TrialStopped(self.trial_id)

    # -- driver-side API -------------------------------------------------------

    def request_stop(self) -> None:
        self._stop.set()

    @property
    def latest(self) -> float | None:
        return self._latest

    def _heartbeat(self, force: bool) -> None:
        if self._client is None:
            return
        now = time.time()
        if not force and now - self._last_hb < self.hb_interval:
            return
        self._last_hb = now
        reply = self._client.call(
            "heartbeat", trial_id=self.trial_id, step=self._step, metric=self._latest
        )
        if isinstance(reply, dict) and reply.get("stop"):
            self._stop.set()

    def finalize(self, metric: float | None = None) -> None:
        if metric is not None:
            with self._lock:
                self._latest = float(metric)
        if self._client is not None:
            try:
                self._heartbeat(force=True)
            finally:
                self._client.close()
                self._client = None


class KerasBatchEnd:
    """Adapter matching the reference's ``KerasBatchEnd(reporter,
    metric=...)`` callback shape (maggy-fashion-mnist-example.ipynb:157)
    for training loops that invoke callbacks with a logs dict."""

    def __init__(self, reporter: Reporter, metric: str = "accuracy"):
        self.reporter = reporter
        self.metric = metric

    def on_batch_end(self, batch: int, logs: dict[str, Any] | None = None) -> None:
        if logs and self.metric in logs:
            self.reporter.broadcast(metric=float(logs[self.metric]), step=batch)
