"""Event-loop HTTP/1.1 server core — the one transport under every
server in the stack.

PR 12 made the relay zero-copy and PR 14 made the *client* side pool
keep-alives; after both, the dominant per-hop cost left on the CPU tier
was the stdlib ``http.server`` transport itself (~5 ms/hop pair,
ROADMAP item 4): per-connection thread churn, ``readline``-based
header parsing through a buffered file object, an ``email.parser``
instantiation per request, and a ``strftime`` per response. The same
``ThreadingHTTPServer + BaseHTTPRequestHandler`` pattern was
copy-instantiated at five sites (serving replicas, the fleet router,
hostd, shardd, the metrics server). This module replaces all five with
one selector-based core:

- **One IO event loop** (``selectors.DefaultSelector`` — epoll on
  Linux, kqueue on BSD/mac) owns the listening socket and every
  connection. Accepts, reads, and writes are all non-blocking; a slow
  peer never holds a thread.
- **Incremental parsing into per-connection buffers.** Bytes land in a
  reusable receive buffer (``recv_into``) and accumulate per
  connection; the parser finds complete header blocks / bodies
  incrementally, so a slowloris-shaped client (one header byte per
  RTT) costs one buffer, not one thread — and is evicted by the idle
  sweep when it overstays ``idle_timeout_s``.
- **Persistent connections with pipelined request queuing.** HTTP/1.1
  keep-alive is the default; a client may send N requests
  back-to-back and the parser queues them all. Responses are written
  strictly in request order per connection (the pipelining contract):
  a response that finishes out of order parks until its predecessors
  are on the wire.
- **Responses as preassembled byte vectors.** A handler returns body
  *bytes*; the core writes ``[header block, body]`` as two
  memoryview-tracked segments and never copies or re-serializes the
  body — the zero-copy relay contract (router bodies pass through
  verbatim) survives the transport.
- **A bounded worker pool runs handlers off the IO loop.** ``workers``
  threads drain a shared FIFO of parsed requests, so a slow predict
  stalls neither accepts nor other connections' reads. The pool is the
  explicit capacity bound the thread-per-connection model never had.

The handler contract (one function per server)::

    route(method, path, headers, body) -> (status, headers, body_bytes)

``headers`` in is a case-insensitive read view of the request headers;
``headers`` out is a plain dict — ``Content-Length`` is computed by the
core (framing is the transport's job; everything else relays verbatim).
A route may return a 4-tuple ``(status, headers, body, after)`` where
``after()`` runs in the worker after the response is queued for write
but before the IO loop is woken to send it — the post-reply hook the
capture taps and shadow probes use (the old handlers ran these after
``wfile.write``; queuing-before-hook keeps response assembly off the
hook's clock while still sequencing the hook before the client can
observe the reply).

Observability: ``hops_tpu_http_connections_total`` /
``hops_tpu_http_requests_total`` / ``hops_tpu_http_keepalive_reuse_total``
/ ``hops_tpu_http_pipelined_requests_total`` /
``hops_tpu_http_open_connections`` (docs/operations.md "Serving
transport"). ``bench.py --hot-path`` measures this core against the
stdlib transport it replaced; tests/test_httpserver.py pins the
edge cases (slowloris, pipelining order, mid-response disconnect,
keep-alive reuse).
"""

from __future__ import annotations

import json
import selectors
import socket
import threading
import time
from collections import deque
from typing import Any, Callable, Iterator, Mapping

from hops_tpu.runtime.logging import get_logger
from hops_tpu.telemetry.metrics import REGISTRY

log = get_logger(__name__)

_m_connections = REGISTRY.counter(
    "hops_tpu_http_connections_total",
    "TCP connections accepted by the event-loop HTTP core, per server",
    labels=("server",),
)
_m_requests = REGISTRY.counter(
    "hops_tpu_http_requests_total",
    "Requests parsed and dispatched by the event-loop HTTP core",
    labels=("server",),
)
_m_reuse = REGISTRY.counter(
    "hops_tpu_http_keepalive_reuse_total",
    "Requests served on an already-used (kept-alive) connection",
    labels=("server",),
)
_m_pipelined = REGISTRY.counter(
    "hops_tpu_http_pipelined_requests_total",
    "Requests that arrived while an earlier request on the same "
    "connection was still in flight (client-side pipelining)",
    labels=("server",),
)
_m_open = REGISTRY.gauge(
    "hops_tpu_http_open_connections",
    "Currently open connections on the event-loop HTTP core",
    labels=("server",),
)

#: (status, headers, body) or (status, headers, body, after_callable).
RouteResult = tuple
Route = Callable[..., RouteResult]

_REASONS = {
    200: "OK", 201: "Created", 204: "No Content", 400: "Bad Request",
    403: "Forbidden", 404: "Not Found", 408: "Request Timeout",
    413: "Payload Too Large", 429: "Too Many Requests",
    500: "Internal Server Error", 501: "Not Implemented",
    503: "Service Unavailable", 504: "Gateway Timeout",
}


class HeaderView(Mapping[str, str]):
    """Case-insensitive read-only view of one request's headers.

    The stdlib handlers exposed ``email.message.Message`` (case
    insensitive); every ported route keeps that lookup behavior without
    paying an ``email.parser`` per request."""

    __slots__ = ("_d",)

    def __init__(self, items: dict[str, str]):
        self._d = items  # keys already lowercased by the parser

    def get(self, key: str, default: Any = None) -> Any:
        return self._d.get(key.lower(), default)

    def __getitem__(self, key: str) -> str:
        return self._d[key.lower()]

    def __contains__(self, key: object) -> bool:
        return isinstance(key, str) and key.lower() in self._d

    def __iter__(self) -> Iterator[str]:
        return iter(self._d)

    def __len__(self) -> int:
        return len(self._d)

    def items(self):  # type: ignore[override]
        return self._d.items()


def assemble(status: int, headers: Mapping[str, str] | None,
             body: bytes) -> list[bytes]:
    """Preassemble one response as ``[header block, body]`` byte
    vectors. ``Content-Length`` and ``Connection`` are the core's
    (framing); caller headers relay verbatim — the body is NEVER
    touched (zero-copy relay contract)."""
    reason = _REASONS.get(status, "Unknown")
    parts = [f"HTTP/1.1 {status} {reason}\r\n"]
    for k, v in (headers or {}).items():
        parts.append(f"{k}: {v}\r\n")
    parts.append(f"Content-Length: {len(body)}\r\n\r\n")
    return ["".join(parts).encode("latin-1"), body]


class _Request:
    __slots__ = ("method", "path", "headers", "body", "close_after", "seq")

    def __init__(self, method: str, path: str, headers: HeaderView,
                 body: bytes, close_after: bool, seq: int):
        self.method = method
        self.path = path
        self.headers = headers
        self.body = body
        self.close_after = close_after  # client asked Connection: close
        self.seq = seq  # per-connection order responses must follow


class _Connection:
    """One accepted socket: its parse buffer, its in-order response
    ledger, and its write cursor. All fields are touched only on the IO
    loop thread except ``done`` (workers fill it under the server's
    response lock)."""

    __slots__ = ("sock", "addr", "inbuf", "served", "next_seq", "next_write",
                 "done", "outq", "out_off", "close_when_drained",
                 "last_activity", "inflight", "broken", "partial_since")

    def __init__(self, sock: socket.socket, addr: Any):
        self.sock = sock
        self.addr = addr
        self.inbuf = bytearray()
        self.served = 0  # requests parsed on this connection
        self.next_seq = 0  # seq for the next parsed request
        self.next_write = 0  # seq whose response goes on the wire next
        self.done: dict[int, tuple[list[bytes], bool]] = {}
        self.outq: deque[memoryview] = deque()
        self.out_off = 0  # bytes of outq[0] already sent
        self.close_when_drained = False
        self.last_activity = time.monotonic()
        self.inflight = 0  # requests handed to workers, not yet written
        self.broken = False  # a 400 was queued; parse no further
        self.partial_since: float | None = None  # incomplete request started


class BadRequest(ValueError):
    """The peer sent bytes that do not parse as HTTP/1.1."""


class HTTPServer:
    """The shared selector-based server core (see module docstring).

    ``route`` is the single handler; ``workers`` bounds handler
    concurrency; ``backlog`` is the listen queue; ``max_pipeline``
    bounds requests queued per connection before reads pause
    (pushback on an abusive pipeliner); ``idle_timeout_s`` evicts
    connections with no completed request and no arriving bytes —
    the slowloris bound. Serving starts in ``__init__``; ``stop()``
    tears everything down."""

    def __init__(
        self,
        route: Route,
        *,
        bind: str = "127.0.0.1",
        port: int = 0,
        name: str = "http",
        workers: int = 16,
        backlog: int = 128,
        max_pipeline: int = 64,
        max_header_bytes: int = 64 * 1024,
        max_body_bytes: int = 256 * 1024 * 1024,
        idle_timeout_s: float = 120.0,
    ):
        self.route = route
        self.name = name
        self.max_pipeline = max_pipeline
        self.max_header_bytes = max_header_bytes
        self.max_body_bytes = max_body_bytes
        self.idle_timeout_s = idle_timeout_s
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((bind, port))
        self._listener.listen(backlog)
        self._listener.setblocking(False)
        self.host, self.port = self._listener.getsockname()[:2]
        self._sel = selectors.DefaultSelector()
        self._sel.register(self._listener, selectors.EVENT_READ, None)
        # Self-pipe: workers wake the IO loop when a response is ready.
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        self._lock = threading.Lock()
        self._stopping = False  # guarded by: self._lock
        self._ready: list[tuple[_Connection, int, list[bytes], bool]] = []  # guarded by: self._lock
        self._conns: set[_Connection] = set()  # IO-loop thread only
        self._qcond = threading.Condition()
        self._queue: deque[tuple[_Connection, _Request]] = deque()  # guarded by: self._qcond
        self._recv_buf = bytearray(256 * 1024)  # one reusable recv window
        self._m_conns = _m_connections.labels(server=name)
        self._m_reqs = _m_requests.labels(server=name)
        self._m_reuse = _m_reuse.labels(server=name)
        self._m_pipe = _m_pipelined.labels(server=name)
        self._m_open = _m_open.labels(server=name)
        self._workers = [
            threading.Thread(target=self._worker, name=f"{name}-worker-{i}",
                             daemon=True)
            for i in range(max(1, workers))
        ]
        for t in self._workers:
            t.start()
        self._io_thread = threading.Thread(
            target=self._io_loop, name=f"{name}-io", daemon=True)
        self._io_thread.start()

    # -- endpoint surface ------------------------------------------------------

    def endpoint(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- the IO loop -----------------------------------------------------------

    def _io_loop(self) -> None:
        while True:
            with self._lock:
                if self._stopping:
                    break
            try:
                events = self._sel.select(timeout=0.5)
            except OSError:
                break
            for key, mask in events:
                if key.data is None:
                    self._accept()
                elif key.data == "wake":
                    try:
                        self._wake_r.recv(4096)
                    except OSError:
                        pass
                else:
                    conn: _Connection = key.data
                    if mask & selectors.EVENT_READ:
                        self._readable(conn)
                    if mask & selectors.EVENT_WRITE and conn.sock.fileno() != -1:
                        self._flush(conn)
            self._drain_ready()
            self._sweep_idle()
        # Teardown on the loop thread: close every socket exactly once.
        for conn in list(self._conns):
            self._close(conn)
        try:
            self._sel.unregister(self._listener)
        except (KeyError, ValueError):
            pass
        self._listener.close()
        try:
            self._sel.unregister(self._wake_r)
        except (KeyError, ValueError):
            pass
        self._wake_r.close()
        self._sel.close()

    def _accept(self) -> None:
        for _ in range(64):  # bounded accept burst per wakeup
            try:
                sock, addr = self._listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            sock.setblocking(False)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _Connection(sock, addr)
            self._conns.add(conn)
            self._sel.register(sock, selectors.EVENT_READ, conn)
            self._m_conns.inc()
            self._m_open.set(len(self._conns))

    def _readable(self, conn: _Connection) -> None:
        try:
            n = conn.sock.recv_into(self._recv_buf)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close(conn)
            return
        if n == 0:  # orderly EOF from the peer
            if not conn.outq and conn.inflight == 0:
                self._close(conn)
            else:
                conn.close_when_drained = True
            return
        conn.last_activity = time.monotonic()
        if conn.broken:
            return  # a 400 is on its way; discard whatever follows
        conn.inbuf += self._recv_buf[:n]
        try:
            self._parse(conn)
        except BadRequest as e:
            self._respond_now(conn, 400, str(e))
        except Exception as e:  # noqa: BLE001 — a parse bug must not kill the loop
            log.warning("%s: parse failure from %s: %s: %s",
                        self.name, conn.addr, type(e).__name__, e)
            self._respond_now(conn, 400, "malformed request")

    def _parse(self, conn: _Connection) -> None:
        """Lift every complete request out of the connection buffer."""
        while True:
            if conn.inflight >= self.max_pipeline:
                return  # pushback: finish some responses first
            buf = conn.inbuf
            if not buf:
                conn.partial_since = None
                return
            head_end = buf.find(b"\r\n\r\n")
            if head_end < 0:
                if len(buf) > self.max_header_bytes:
                    raise BadRequest("header block too large")
                if conn.partial_since is None:
                    conn.partial_since = time.monotonic()
                return
            head = bytes(buf[:head_end])
            lines = head.split(b"\r\n")
            try:
                method_b, path_b, version_b = lines[0].split(b" ", 2)
            except ValueError:
                raise BadRequest("malformed request line") from None
            if not version_b.startswith(b"HTTP/1."):
                raise BadRequest(f"unsupported version {version_b[:20]!r}")
            headers: dict[str, str] = {}
            for line in lines[1:]:
                if not line:
                    continue
                k, sep, v = line.partition(b":")
                if not sep:
                    raise BadRequest("malformed header line")
                headers[k.decode("latin-1").strip().lower()] = (
                    v.decode("latin-1").strip())
            if "transfer-encoding" in headers:
                # The pool/clients always frame with Content-Length;
                # chunked decode is complexity none of the five sites
                # needs. Refuse loudly rather than misparse.
                raise BadRequest("chunked transfer encoding unsupported")
            try:
                length = int(headers.get("content-length", "0") or "0")
            except ValueError:
                raise BadRequest("malformed Content-Length") from None
            if length < 0 or length > self.max_body_bytes:
                raise BadRequest("body too large")
            total = head_end + 4 + length
            if len(buf) < total:
                if conn.partial_since is None:
                    conn.partial_since = time.monotonic()
                return  # body still arriving
            body = bytes(buf[head_end + 4:total])
            del buf[:total]
            conn.partial_since = None
            close_after = (
                headers.get("connection", "").lower() == "close"
                or version_b == b"HTTP/1.0"
            )
            req = _Request(method_b.decode("latin-1"),
                           path_b.decode("latin-1"), HeaderView(headers),
                           body, close_after, conn.next_seq)
            conn.next_seq += 1
            if conn.served > 0:
                self._m_reuse.inc()
            if conn.inflight > 0:
                self._m_pipe.inc()
            conn.served += 1
            conn.inflight += 1
            self._m_reqs.inc()
            with self._qcond:
                self._queue.append((conn, req))
                self._qcond.notify()

    def _respond_now(self, conn: _Connection, status: int, msg: str) -> None:
        """IO-loop-side error reply (parse failures): queue a canned
        response at the next write slot and close after the drain."""
        body = json.dumps({"error": msg}).encode()
        vec = assemble(status, {"Content-Type": "application/json"}, body)
        with self._lock:
            self._ready.append((conn, conn.next_seq, vec, True))
        conn.next_seq += 1
        conn.inflight += 1
        conn.broken = True
        conn.inbuf.clear()  # poisoned stream: parse no further
        self._drain_ready()

    # -- workers ---------------------------------------------------------------

    def _worker(self) -> None:
        while True:
            with self._qcond:
                while not self._queue:
                    with self._lock:
                        if self._stopping:
                            return
                    self._qcond.wait(timeout=0.5)
                conn, req = self._queue.popleft()
            with self._lock:
                if self._stopping:
                    return
            after = None
            try:
                result = self.route(req.method, req.path, req.headers,
                                    req.body)
                if len(result) == 4:
                    status, hdrs, body, after = result
                else:
                    status, hdrs, body = result
                if not isinstance(body, (bytes, bytearray, memoryview)):
                    raise TypeError(
                        f"route returned {type(body).__name__} body; the "
                        "transport relays bytes only")
                vec = assemble(int(status), hdrs, bytes(body))
            except Exception as e:  # noqa: BLE001 — a handler fault must reach
                # the client as a 500 (breaker food), never kill the worker
                log.warning("%s: handler %s %s failed: %s: %s", self.name,
                            req.method, req.path, type(e).__name__, e)
                body = json.dumps(
                    {"error": f"{type(e).__name__}: {e}"}).encode()
                vec = assemble(500, {"Content-Type": "application/json"}, body)
            with self._lock:
                self._ready.append((conn, req.seq, vec, req.close_after))
            # The post-reply hook runs after the response is queued but
            # BEFORE the IO loop is woken: the client cannot observe the
            # reply until the wake fires, which gives the capture taps a
            # deterministic happens-before against anything the client
            # does next (e.g. finalizing a workload capture the moment
            # its request returns). Hooks are quick by contract — slow
            # work (shadow probes) spawns its own thread.
            if after is not None:
                try:
                    after()
                except Exception as e:  # noqa: BLE001 — post-reply taps are
                    # best-effort; the response is already assembled
                    log.warning("%s: post-reply hook failed: %s: %s",
                                self.name, type(e).__name__, e)
            try:
                self._wake_w.send(b"x")
            except OSError:
                pass

    # -- response sequencing + writes (IO loop thread) -------------------------

    def _drain_ready(self) -> None:
        with self._lock:
            ready, self._ready = self._ready, []
        for conn, seq, vec, close_after in ready:
            conn.done[seq] = (vec, close_after)
        touched = {conn for conn, _, _, _ in ready}
        for conn in touched:
            if conn not in self._conns:
                continue
            # Release every response that is next in line (pipelining:
            # strictly request order, holes park their successors).
            while conn.next_write in conn.done:
                vec, close_after = conn.done.pop(conn.next_write)
                conn.next_write += 1
                conn.inflight -= 1
                for seg in vec:
                    if len(seg):
                        conn.outq.append(memoryview(seg))
                if close_after:
                    conn.close_when_drained = True
            self._flush(conn)

    def _flush(self, conn: _Connection) -> None:
        try:
            while conn.outq:
                seg = conn.outq[0]
                n = conn.sock.send(seg[conn.out_off:])
                conn.out_off += n
                if conn.out_off < len(seg):
                    break  # kernel buffer full: wait for EVENT_WRITE
                conn.outq.popleft()
                conn.out_off = 0
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            # Mid-response disconnect: drop the connection, keep serving
            # everyone else (the worker that produced this response has
            # already moved on).
            self._close(conn)
            return
        conn.last_activity = time.monotonic()
        want = selectors.EVENT_READ
        if conn.outq:
            want |= selectors.EVENT_WRITE
        elif conn.close_when_drained and conn.inflight == 0:
            self._close(conn)
            return
        try:
            self._sel.modify(conn.sock, want, conn)
        except (KeyError, ValueError, OSError):
            pass

    def _sweep_idle(self) -> None:
        if self.idle_timeout_s is None:
            return
        now = time.monotonic()
        for conn in list(self._conns):
            idle = (conn.inflight == 0 and not conn.outq
                    and now - conn.last_activity > self.idle_timeout_s)
            # The slowloris drip keeps last_activity fresh one byte at
            # a time — the clock that matters is how long ONE request
            # has been incomplete, not how recently bytes arrived.
            dripping = (conn.partial_since is not None
                        and now - conn.partial_since > self.idle_timeout_s)
            if idle or dripping:
                self._close(conn)

    def _close(self, conn: _Connection) -> None:
        if conn not in self._conns:
            return
        self._conns.discard(conn)
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        self._m_open.set(len(self._conns))

    # -- lifecycle -------------------------------------------------------------

    def stop(self) -> None:
        with self._lock:
            if self._stopping:
                return
            self._stopping = True
        with self._qcond:
            self._qcond.notify_all()
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass
        self._io_thread.join(timeout=5)
        for t in self._workers:
            t.join(timeout=5)
        self._wake_w.close()

    # Aliases for the stdlib server surface the five sites grew up on,
    # so ported call sites read naturally during review.
    shutdown = stop

    def server_close(self) -> None:
        pass  # stop() already closed every socket
