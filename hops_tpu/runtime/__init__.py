"""Runtime core: topology, config, logging, run directories, filesystem.

This layer replaces the reference's L2 utility layer (``hops`` modules,
SURVEY.md §1 L2, §2.2) — environment discovery, security material,
filesystem and project scoping — re-imagined for a TPU slice instead of
a Spark/YARN cluster.
"""

from hops_tpu.runtime import (  # noqa: F401
    config,
    devices,
    faultinject,
    fs,
    logging,
    resilience,
    rundir,
)
