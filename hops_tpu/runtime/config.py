"""Typed configuration layer.

The reference had four uncoordinated config idioms — templated JSON job
configs, argparse CLIs, Scallop args, properties files (SURVEY.md §5
"Config / flag system"). This module unifies them: dataclass-backed typed
configs that load from (in priority order) explicit kwargs > CLI-style
``key=value`` overrides > environment (``HOPS_TPU_<KEY>``) > JSON file >
defaults, with dotted-path access for nested sections.
"""

from __future__ import annotations

import dataclasses
import json
import os
from pathlib import Path
from typing import Any, TypeVar, get_type_hints

T = TypeVar("T")

_ENV_PREFIX = "HOPS_TPU_"


def _coerce(value: Any, typ: Any) -> Any:
    """Coerce a string/JSON value to the annotated dataclass field type."""
    if typ is Any or value is None:
        return value
    # Unwrap Optional / unions: coerce to the first non-None member.
    import types as _types
    import typing as _typing

    if isinstance(typ, _types.UnionType) or getattr(typ, "__origin__", None) is _typing.Union:
        members = [a for a in typ.__args__ if a is not type(None)]
        for i, m in enumerate(members):
            try:
                return _coerce(value, m)
            except (ValueError, TypeError):
                if i == len(members) - 1:
                    raise
        return value
    origin = getattr(typ, "__origin__", None)
    if dataclasses.is_dataclass(typ):
        if isinstance(value, str):
            value = json.loads(value)
        if isinstance(value, dict):
            return from_dict(typ, value)
    if origin in (list, tuple) and isinstance(value, str):
        try:
            value = json.loads(value)
        except json.JSONDecodeError:
            value = value.split(",")  # CLI form: "mesh=4,2" / "axes=data,model"
    if origin in (list, tuple):
        if not isinstance(value, (list, tuple)):
            value = [value]  # single-element override: "mesh=4"
        args = getattr(typ, "__args__", ())
        elem = args[0] if args and args[0] is not Ellipsis else Any
        coerce_elem = elem if elem in (int, float, str, bool) else Any
        value = [
            _coerce(v.strip() if isinstance(v, str) else v, coerce_elem) for v in value
        ]
        return tuple(value) if origin is tuple else value
    if typ is bool and isinstance(value, str):
        return value.lower() in ("1", "true", "yes", "on")
    if typ in (int, float, str) and not isinstance(value, typ):
        return typ(value)
    return value


def from_dict(cls: type[T], data: dict[str, Any]) -> T:
    """Build dataclass ``cls`` from a (possibly nested) dict, coercing types."""
    hints = get_type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name in data:
            kwargs[f.name] = _coerce(data[f.name], hints.get(f.name, Any))
    return cls(**kwargs)


def to_dict(cfg: Any) -> dict[str, Any]:
    return dataclasses.asdict(cfg)


def _apply_env(cls: type, data: dict[str, Any]) -> None:
    for f in dataclasses.fields(cls):
        env_key = _ENV_PREFIX + f.name.upper()
        if env_key in os.environ:
            data[f.name] = os.environ[env_key]


def _set_dotted(data: dict[str, Any], key: str, value: Any) -> None:
    parts = key.split(".")
    node = data
    for p in parts[:-1]:
        node = node.setdefault(p, {})
    node[parts[-1]] = value


def load(
    cls: type[T],
    path: str | Path | None = None,
    overrides: list[str] | dict[str, Any] | None = None,
    **kwargs: Any,
) -> T:
    """Load a config dataclass from file + env + overrides + kwargs.

    ``overrides`` accepts ``["train.lr=0.1", "mesh=4,2"]``-style strings
    (the CLI form) or a plain dict with dotted keys.
    """
    data: dict[str, Any] = {}
    if path is not None:
        data.update(json.loads(Path(path).read_text()))
    _apply_env(cls, data)
    if overrides:
        items = (
            overrides.items()
            if isinstance(overrides, dict)
            else (kv.split("=", 1) for kv in overrides)
        )
        for k, v in items:
            _set_dotted(data, k, v)
    data.update(kwargs)
    return from_dict(cls, data)


def save(cfg: Any, path: str | Path) -> None:
    Path(path).write_text(json.dumps(to_dict(cfg), indent=2, default=str))


@dataclasses.dataclass
class RuntimeConfig:
    """Global runtime knobs; the root config most subsystems hang off."""

    project: str = "default"
    workspace: str = ""  # resolved lazily by fs.workspace_root()
    seed: int = 0
    # Default dtype for compute on the MXU.
    compute_dtype: str = "bfloat16"
    # Mesh axis names used by the distribution layer, outermost first.
    mesh_axes: tuple[str, ...] = ("data", "model")
    log_level: str = "INFO"


# Initialized through load() so the documented precedence applies from
# the start: env (HOPS_TPU_PROJECT / HOPS_TPU_WORKSPACE, as exported to
# job children and serving hosts) > field defaults; an explicit
# configure(...) later still overrides either. A malformed env var must
# not make the package unimportable — warn and fall back to defaults.
try:
    _current = load(RuntimeConfig)
except Exception as _env_err:  # noqa: BLE001
    import warnings

    warnings.warn(f"ignoring invalid HOPS_TPU_* environment: {_env_err}")
    _current = RuntimeConfig()


def runtime() -> RuntimeConfig:
    return _current


def configure(**kwargs: Any) -> RuntimeConfig:
    """Update the process-global runtime config in place."""
    global _current
    _current = dataclasses.replace(_current, **kwargs)
    if "log_level" in kwargs:
        import logging as _stdlog

        _stdlog.getLogger("hops_tpu").setLevel(_current.log_level)
    return _current
