"""Flight recorder: a bounded ring of the platform's last notable events.

Post-incident debugging of the chaos paths (retries, breaker trips,
preemptions, quarantines) used to mean grepping logs with no causal
thread. This module is the black box instead: every resilience and
fault-injection site appends one small structured event — monotonic
sequence number, wall time, kind, the active trace id when the event
fired under a traced request, and a payload — into a bounded in-memory
ring. Nothing is written in steady state; the ring is

- served live at ``GET /debug/flight`` (telemetry/export.py mounts it
  beside ``/metrics`` on every serving, replica, and router port), and
- **dumped to the rundir on unhandled failure** once
  :func:`install_crash_handler` has chained itself into
  ``sys.excepthook`` / ``threading.excepthook`` (``run_preemptible``
  does this), so a crashed host leaves its last-N-events story behind.

Event kinds are a closed, documented catalog — docs/operations.md
"Tracing & debugging" lists every kind, and the graftlint
``debug-surface-docs`` rule keeps code and catalog honest. Current
kinds: ``fault_fired``, ``retry``, ``giveup``, ``deadline_exceeded``,
``breaker_transition``, ``drain``, ``quarantine``, ``preemption``,
``recovery``, ``replica_state``, ``rollout``, ``dispatch_failure``,
``span_replayed``, ``eval_gate``, ``cutover``, ``crash``,
``partition``, ``fence``, ``generation``, ``generation_rejected``.

Stdlib-only (this is imported by the same hot paths ``faultinject``
rides); the trace-id peek goes through ``telemetry.tracing``, which is
stdlib-only too.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any

from hops_tpu.runtime.logging import get_logger
from hops_tpu.telemetry import tracing

log = get_logger(__name__)

def _env_capacity(default: int = 2048) -> int:
    # Malformed env must degrade to the default, not kill every process
    # that imports this module (tracing._env_float holds the same line).
    try:
        return int(os.environ.get("HOPS_TPU_FLIGHT_RING", default))
    except ValueError:
        return default


#: Default ring capacity (events, not bytes — events are small dicts).
DEFAULT_CAPACITY = _env_capacity()


class FlightRecorder:
    """Thread-safe bounded ring of structured events.

    One process-global :data:`FLIGHT` serves the stack; tests may build
    private ones. ``record`` is cheap (one lock + deque append) and
    NEVER raises — the black box must not take the plane down.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        # RLock, not Lock: record() is called from signal handlers
        # (PreemptionGuard), which run on the main thread — if that
        # thread was itself inside record() when the signal landed, a
        # plain Lock would deadlock on re-acquire.
        self._lock = threading.RLock()
        self._seq = 0  # guarded by: self._lock
        # guarded by: self._lock
        self._ring: deque[dict[str, Any]] = deque(maxlen=capacity)

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    def record(self, kind: str, **data: Any) -> dict[str, Any] | None:
        """Append one event; returns it (None if recording failed —
        swallowed by contract, a diagnostic layer must never fail the
        operation it observes)."""
        try:
            event: dict[str, Any] = {
                "time": time.time(),
                "kind": kind,
                "trace_id": tracing.current_trace_id(),
                "data": data,
            }
            with self._lock:
                self._seq += 1
                event["seq"] = self._seq
                self._ring.append(event)
            return event
        except Exception:  # graftlint: disable=swallowed-exception
            return None  # by contract: see docstring

    def events(self, kind: str | None = None,
               after_seq: int = 0) -> list[dict[str, Any]]:
        """Events in causal (sequence) order, optionally filtered by
        kind and/or newer-than ``after_seq`` (how tests scope to their
        own run against the process-global ring)."""
        with self._lock:
            rows = list(self._ring)
        return [
            e for e in rows
            if e["seq"] > after_seq and (kind is None or e["kind"] == kind)
        ]

    @property
    def seq(self) -> int:
        """The newest sequence number (0 = empty): snapshot this before
        an operation, then ``events(after_seq=...)`` scopes to it."""
        with self._lock:
            return self._seq

    def snapshot(self) -> dict[str, Any]:
        """The JSON body ``GET /debug/flight`` serves."""
        events = self.events()
        return {
            "time": time.time(),
            "pid": os.getpid(),
            "capacity": self.capacity,
            "events": events,
        }

    def dump(self, path: str | Path | None = None,
             reason: str = "manual") -> Path | None:
        """Write the ring to ``path`` (default: the active rundir's
        logdir, ``flight_<pid>.json``). Returns the written path, or
        None on failure — dumping happens on the way DOWN; it must not
        mask the original crash."""
        try:
            if path is None:
                from hops_tpu.runtime import rundir

                path = Path(rundir.logdir()) / f"flight_{os.getpid()}.json"
            path = Path(path)
            path.parent.mkdir(parents=True, exist_ok=True)
            body = self.snapshot()
            body["reason"] = reason
            path.write_text(json.dumps(body, indent=2, default=str))
            log.warning("flight recorder dumped %d event(s) to %s (%s)",
                        len(body["events"]), path, reason)
            return path
        except Exception:  # graftlint: disable=swallowed-exception
            # By contract: a crash-path dump failure must not replace
            # the original exception — it is already being reported.
            return None

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


#: The process-global recorder every subsystem records into.
FLIGHT = FlightRecorder()


def _crash_flush_workload(dump_path: Path | None) -> Path | None:
    """Crash-path companion to the flight dump: finalize any active
    workload-capture segment and manifest (so the crashed run's
    traffic is replayable post-mortem) and leave a pointer file next
    to the flight dump naming the artifact. Never raises — this runs
    on the way DOWN."""
    try:
        from hops_tpu.telemetry import workload

        artifact = workload.crash_flush()
        if artifact is None:
            return None
        log.warning("workload capture flushed for post-mortem replay: %s",
                    artifact)
        if dump_path is not None:
            pointer = Path(dump_path).with_name(
                f"workload_{os.getpid()}.json")
            pointer.write_text(json.dumps(
                {"workload_artifact": str(artifact),
                 "flight_dump": str(dump_path)}, indent=2))
        return artifact
    except Exception:  # graftlint: disable=swallowed-exception
        # By contract: a crash-path flush failure must not replace the
        # original exception — it is already being reported.
        return None


def record(kind: str, **data: Any) -> dict[str, Any] | None:
    """Record onto the process-global :data:`FLIGHT` ring."""
    return FLIGHT.record(kind, **data)


_install_lock = threading.Lock()
_installed = False  # guarded by: _install_lock


def install_crash_handler() -> bool:
    """Chain the flight-recorder dump into ``sys.excepthook`` and
    ``threading.excepthook``: any unhandled exception records a
    ``crash`` event, dumps the ring to the rundir, and finalizes any
    active workload-capture segment + manifest (with a
    ``workload_<pid>.json`` pointer next to the flight dump) so the
    crashed run's traffic is replayable post-mortem — all before the
    previous hook runs. Idempotent; returns True when this call
    installed it."""
    global _installed
    with _install_lock:
        if _installed:
            return False
        _installed = True
        prev_sys = sys.excepthook
        prev_threading = threading.excepthook

        def _sys_hook(exc_type, exc, tb):
            FLIGHT.record("crash", where="main",
                          error=f"{exc_type.__name__}: {exc}")
            dumped = FLIGHT.dump(reason=f"unhandled {exc_type.__name__}")
            _crash_flush_workload(dumped)
            prev_sys(exc_type, exc, tb)

        def _threading_hook(args):
            FLIGHT.record(
                "crash",
                where=getattr(args.thread, "name", "?"),
                error=f"{args.exc_type.__name__}: {args.exc_value}",
            )
            dumped = FLIGHT.dump(reason=f"unhandled {args.exc_type.__name__} "
                                        f"in thread")
            _crash_flush_workload(dumped)
            prev_threading(args)

        sys.excepthook = _sys_hook
        threading.excepthook = _threading_hook
        return True
