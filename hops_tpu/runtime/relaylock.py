"""Single-tenant relay lock: serialize every client of the TPU relay.

The axon relay serves ONE tenant; two clients racing it — or a client
killed mid-compile — wedge it for hours (BENCHMARKS.md relay incident
log: both round-4 wedges were self-inflicted collisions/kills). Round 4
stated the discipline in prose; this module enforces it in code, per
the round-4 review: one lock, acquired by everything that touches the
relay (`bench.py`, `hw_measure.py`, `hw_watch.py`,
`examples/decode_bench.py`), refusing to start while another holder is
live, and never wrapped in `timeout`.

Mechanics
---------
* The lock is a file (default `<repo>/.relay.lock`; override with
  `$HOPS_TPU_RELAY_LOCK` for tests) created with `O_CREAT|O_EXCL` —
  atomic on POSIX — holding `{pid, purpose, ts}` for diagnostics.
* A second acquire by a different process raises `RelayBusy` naming
  the live owner, *without* touching the relay.
* Stale locks (owner pid no longer alive) are broken automatically:
  a crash must not require manual cleanup.
* Holders export `$HOPS_TPU_RELAY_TOKEN` so their *children* (e.g.
  `hw_measure.py` running `bench.py --no-probe`) pass through instead
  of deadlocking against their own parent's lock.

Reference role: the reference serializes GPU benchmark runs by having
exactly one Spark executor per GPU (`benchmark.ipynb` under
MirroredStrategy); here the scarce resource is the relay itself, so the
mutual exclusion lives client-side.
"""

from __future__ import annotations

import json
import os
import time
from contextlib import contextmanager
from pathlib import Path
from typing import Iterator

ENV_LOCK_PATH = "HOPS_TPU_RELAY_LOCK"
ENV_TOKEN = "HOPS_TPU_RELAY_TOKEN"

#: How long an existing-but-unparsable lock file (empty / corrupt JSON)
#: may persist before it is treated as stale and broken. Long enough to
#: never race a healthy acquirer's create->write window (microseconds),
#: short enough that a crash mid-write can't wedge every future client.
UNREADABLE_GRACE_S = 1.0


def lock_path() -> Path:
    override = os.environ.get(ENV_LOCK_PATH)
    if override:
        return Path(override)
    return Path(__file__).resolve().parents[2] / ".relay.lock"


class RelayBusy(RuntimeError):
    """Another live process holds the relay lock."""

    def __init__(self, owner: dict):
        self.owner = owner
        super().__init__(
            f"relay locked by pid {owner.get('pid')} "
            f"({owner.get('purpose', '?')}) since {owner.get('ts', '?')} — "
            "refusing to race the single-tenant relay; wait for the holder "
            "to finish naturally (NEVER kill it: a killed client wedges "
            "the relay)"
        )


def _read_owner(path: Path) -> dict | None:
    try:
        return json.loads(path.read_text())
    except (OSError, ValueError):
        return None  # vanished or mid-write; caller retries


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    return True


def _break_stale(path: Path, stale_pid: int) -> None:
    """Unlink the lock iff it still names `stale_pid` and that pid is dead.

    Serialized under an flock'd guard file: two racers that both
    observed the same stale lock must not double-break — the loser's
    unlink would otherwise remove a NEW holder's freshly created lock,
    putting two clients inside the critical section (the exact
    collision this module exists to prevent). Under the guard, the
    re-read makes the unlink conditional on the lock still being the
    stale one.
    """
    import fcntl

    guard = path.with_name(path.name + ".guard")
    with open(guard, "w") as g:
        fcntl.flock(g, fcntl.LOCK_EX)
        owner = _read_owner(path)
        if (
            owner is not None
            and owner.get("pid") == stale_pid
            and not _pid_alive(stale_pid)
        ):
            try:
                path.unlink()
            except FileNotFoundError:
                pass


def _break_unreadable(path: Path, grace_s: float) -> None:
    """Unlink a lock that exists but cannot be parsed, iff it has been
    sitting unreadable for at least ``grace_s`` (by mtime). Serialized
    under the same flock'd guard as :func:`_break_stale` so two racers
    can't double-break and unlink a NEW holder's fresh lock; the mtime
    re-check under the guard keeps a mid-write (fresh, briefly-empty)
    lock safe."""
    import fcntl

    guard = path.with_name(path.name + ".guard")
    with open(guard, "w") as g:
        fcntl.flock(g, fcntl.LOCK_EX)
        try:
            if (
                _read_owner(path) is None
                # Wall-vs-mtime on purpose: st_mtime IS wall clock, so
                # the ages are on the same (steppable) timeline.
                and time.time() - path.stat().st_mtime >= grace_s  # graftlint: disable=wall-clock-deadline
            ):
                path.unlink()
        except FileNotFoundError:
            pass  # vanished while we checked: nothing to break


def current_owner() -> dict | None:
    """The live holder's `{pid, purpose, ts}`, or None if the lock is free.

    Side effect: breaks (removes) a stale lock whose owner pid is dead.
    """
    path = lock_path()
    if not path.exists():
        return None
    owner = _read_owner(path)
    if owner is None:
        return None
    pid = owner.get("pid")
    if isinstance(pid, int) and not _pid_alive(pid):
        # Crashed holder: break the lock so a crash never needs manual
        # cleanup. Children of the dead holder may linger, but they
        # inherited the token and will finish on their own — the next
        # holder's pre-run probe detects an unhealthy relay anyway.
        _break_stale(path, pid)
        return None
    return owner


@contextmanager
def relay_lock(purpose: str, wait_s: float = 0.0, poll_s: float = 5.0) -> Iterator[None]:
    """Hold the relay for `purpose`; children inherit via $HOPS_TPU_RELAY_TOKEN.

    `wait_s=0` refuses immediately when busy (the hw_* entry points);
    `wait_s>0` polls until the holder exits (bench.py's driver run,
    which would rather wait out a sweep than go red).

    Raises `RelayBusy` if still held at the deadline.
    """
    path = lock_path()
    if os.environ.get(ENV_TOKEN):
        # We are a child of the holder (or a re-entrant caller): the
        # parent serializes relay access for us.
        yield
        return
    deadline = time.monotonic() + wait_s
    unreadable_since: float | None = None
    while True:
        owner = current_owner()  # also breaks stale locks
        if owner is None:
            try:
                fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                # The lock exists but current_owner() saw no owner:
                # either it vanished mid-race (retry immediately) or it
                # is unreadable (empty/corrupt — a crash mid-write).
                # The latter used to busy-spin here forever; now it
                # sleeps, raises RelayBusy at the deadline, and breaks
                # a persistently unreadable lock after a grace period.
                if _read_owner(path) is not None or not path.exists():
                    unreadable_since = None
                    continue  # readable/gone: the next probe classifies it
                now = time.monotonic()
                if unreadable_since is None:
                    unreadable_since = now
                elif now - unreadable_since >= UNREADABLE_GRACE_S:
                    _break_unreadable(path, UNREADABLE_GRACE_S)
                    unreadable_since = None
                    continue
                if now >= deadline:
                    raise RelayBusy({
                        "pid": None,
                        "purpose": f"unreadable lock file at {path} "
                                   "(empty or corrupt; not holder JSON)",
                        "ts": "?",
                    })
                time.sleep(min(poll_s, 0.05,
                               max(0.01, deadline - time.monotonic())))
                continue
            unreadable_since = None
            with os.fdopen(fd, "w") as f:
                json.dump(
                    {"pid": os.getpid(), "purpose": purpose,
                     "ts": time.strftime("%Y-%m-%d %H:%M:%S")},
                    f,
                )
            break
        if time.monotonic() >= deadline:
            raise RelayBusy(owner)
        time.sleep(min(poll_s, max(0.1, deadline - time.monotonic())))
    os.environ[ENV_TOKEN] = str(os.getpid())
    try:
        yield
    finally:
        os.environ.pop(ENV_TOKEN, None)
        owner = _read_owner(path)
        if owner and owner.get("pid") == os.getpid():
            try:
                path.unlink()
            except FileNotFoundError:
                pass
