"""Tracing, hang detection, and determinism.

SURVEY.md §5 found the reference's story thin: TensorBoard profiling
only (``profile_batch='5,10'`` in Keras callbacks), **no race/deadlock
tooling**, and no deterministic mode. The TPU equivalents:

- :func:`trace` — ``jax.profiler`` trace into the active run's logdir,
  viewable in TensorBoard/XProf exactly where the reference's profiler
  window landed (reference: notebooks/ml/Experiment/Tensorflow/
  mnist.ipynb:172-173).
- :class:`Watchdog` — collective-deadlock detector. SPMD programs hang,
  not crash, when one host misses a collective; the watchdog fires when
  the step loop stops heartbeating, dumps every Python thread's stack,
  and optionally kills the process so the job scheduler can retry.
- :func:`deterministic_mode` — one switch for bitwise-reproducible runs
  (XLA deterministic ops + seeded ``jax.random`` keys), the stand-in
  for race detection on a platform where the compiler owns scheduling.
- :class:`FlightRecorder` / :data:`FLIGHT` — the crash/fault flight
  recorder: a bounded ring of recent structured events (faults fired,
  breaker transitions, retries, drains, quarantines, preemptions),
  dumped to the rundir on unhandled failure and served at
  ``GET /debug/flight``. Lives in the stdlib-only
  :mod:`hops_tpu.runtime.flight` (this module imports jax; serving
  hosts and the fleet router must not) and is re-exported here as the
  diagnostics surface.
"""

from __future__ import annotations

import contextlib
import faulthandler
import os
import sys
import threading
import time
from typing import Iterator

import jax

from hops_tpu.runtime import rundir
from hops_tpu.runtime.flight import (  # noqa: F401 — diagnostics surface
    FLIGHT,
    FlightRecorder,
    install_crash_handler,
)
from hops_tpu.runtime.logging import get_logger

log = get_logger(__name__)


@contextlib.contextmanager
def trace(logdir: str | None = None) -> Iterator[str]:
    """Capture a profiler trace for the with-block into ``logdir``
    (default: ``<active run>/trace``)."""
    target = logdir or os.path.join(rundir.logdir(), "trace")
    os.makedirs(target, exist_ok=True)
    jax.profiler.start_trace(target)
    try:
        yield target
    finally:
        jax.profiler.stop_trace()


class Watchdog:
    """Detects a stalled step loop (the usual face of a collective deadlock).

    The training loop calls :meth:`heartbeat` once per step; a daemon
    thread fires after ``timeout_s`` without one, logs every thread's
    stack (so the hung collective is visible in the trace), and calls
    ``on_hang`` — default: dump + ``os._exit(42)`` when ``fatal`` else
    just log, letting an external supervisor restart the host. This is
    the framework-level replacement for the failure detection the
    reference outsourced to YARN container restarts (SURVEY.md §5).

    ``watch_heartbeat_gauge`` reads the telemetry heartbeat gauge
    (maintained by ``runtime/preemption.run_preemptible`` and
    ``telemetry.StepTimer``) instead of requiring explicit
    :meth:`heartbeat` calls — a watchdog in ANY thread of the process
    can then supervise an instrumented loop it has no handle on. Pass
    the LOOP NAME (e.g. ``"preemptible"``) to watch one specific loop;
    ``True`` accepts a beat from any loop in the process (process
    liveness — in multi-loop processes a healthy loop then masks a hung
    one, so prefer the name form). The comparison uses the gauge's
    monotonic twin, immune to wall-clock steps. Falls back to the
    explicit clock until the gauge first beats.
    """

    def __init__(self, timeout_s: float = 300.0, fatal: bool = False, on_hang=None,
                 watch_heartbeat_gauge: bool | str = False):
        self.timeout_s = timeout_s
        self.fatal = fatal
        self.on_hang = on_hang
        self.watch_heartbeat_gauge = watch_heartbeat_gauge
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._fired = False
        self._thread: threading.Thread | None = None

    def heartbeat(self) -> None:
        self._last = time.monotonic()

    def _beat_age(self) -> float:
        """Seconds since the newest heartbeat: the explicit clock,
        optionally superseded by the telemetry gauge (whichever beat
        most recently wins, so arming the watchdog before the first
        tick doesn't fire on gauge silence)."""
        age = time.monotonic() - self._last
        if self.watch_heartbeat_gauge:
            from hops_tpu.telemetry.metrics import REGISTRY
            from hops_tpu.telemetry.spans import HEARTBEAT_MONO_GAUGE

            want = (
                self.watch_heartbeat_gauge
                if isinstance(self.watch_heartbeat_gauge, str) else None
            )
            gauge = REGISTRY.get(HEARTBEAT_MONO_GAUGE)
            if gauge is not None:
                # Read via samples() — value(loop=...) would CREATE a
                # zero child and pollute the export.
                beats = [
                    v for _s, labels, v in gauge.samples()
                    if v > 0 and (want is None or labels.get("loop") == want)
                ]
                if beats:
                    age = min(age, time.monotonic() - max(beats))
        return age

    @property
    def fired(self) -> bool:
        return self._fired

    def _watch(self) -> None:
        while not self._stop.wait(min(self.timeout_s / 4, 5.0)):
            if self._beat_age() > self.timeout_s:
                self._fired = True
                log.error(
                    "watchdog: no heartbeat for %.0fs — possible collective "
                    "deadlock; dumping thread stacks",
                    self.timeout_s,
                )
                faulthandler.dump_traceback(file=sys.stderr)
                if self.on_hang is not None:
                    self.on_hang()
                elif self.fatal:
                    os._exit(42)
                return

    def start(self) -> "Watchdog":
        self._last = time.monotonic()
        self._thread = threading.Thread(target=self._watch, daemon=True, name="hops-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


@contextlib.contextmanager
def deterministic_mode(seed: int = 0) -> Iterator[jax.Array]:
    """Bitwise-reproducible execution for the with-block.

    Yields a seeded root PRNG key. XLA scheduling on TPU is already
    deterministic for a fixed program; the remaining nondeterminism
    (autotuned reductions on other backends, Python hash order) is
    pinned here.
    """
    prev = jax.config.jax_default_prng_impl
    os.environ.setdefault("TF_DETERMINISTIC_OPS", "1")
    jax.config.update("jax_default_prng_impl", "threefry2x32")
    try:
        yield jax.random.PRNGKey(seed)
    finally:
        jax.config.update("jax_default_prng_impl", prev)


# -- roofline analysis over profiler traces ----------------------------------

#: Peak specs per TPU generation for roofline bounds (bf16 matmul
#: TFLOP/s, HBM GB/s). v5e figures are the published 197/819; other
#: rows are fallbacks so the report still renders off-TPU.
_PEAKS = {
    "v5 lite": (197e12, 819e9),
    "v5e": (197e12, 819e9),
    "v5p": (459e12, 2765e9),
    "v4": (275e12, 1228e9),
    "cpu": (1e12, 100e9),
}


def device_peaks(kind: str | None = None) -> tuple[float, float] | None:
    """(bf16 matmul FLOP/s, HBM bytes/s) peaks for a device kind.

    ``kind`` defaults to the local backend's ``device_kind``; returns
    None when the generation isn't tabulated — callers must not guess
    a roof (an MFU% against the wrong generation's peak overstates the
    headline). Single source for every peak lookup (roofline_report,
    bench.py --lm).
    """
    if kind is None:
        kind = jax.devices()[0].device_kind
    return next((v for k, v in _PEAKS.items() if k in kind.lower()), None)


def _find_trace_file(trace_dir: str) -> str:
    import glob

    files = sorted(glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True))
    if not files:
        raise FileNotFoundError(f"no *.trace.json.gz under {trace_dir}")
    return files[-1]


def _device_op_rows(trace_dir: str) -> tuple[str, list[dict]]:
    """Parse a :func:`trace` capture into per-op rows for ONE device pid.

    Shared by :func:`roofline_report` and :func:`top_ops` so the
    load-bearing filters live in one place: one device pid only (in
    SPMD every chip runs the same program — summing all pids would
    multiply time and bytes by the chip count), program envelopes
    (``jit_fn(...)``, bare step numbers) skipped, and the ``*-start``
    halves of async pairs skipped (bytes live on the ``-done`` event).
    """
    import gzip
    import json
    import re

    with gzip.open(_find_trace_file(trace_dir)) as f:
        events = json.load(f)["traceEvents"]
    pid_names = {
        e["pid"]: e["args"].get("name", "")
        for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    device_pids = set(sorted(p for p, n in pid_names.items() if "TPU" in n or "GPU" in n)[:1])
    device_name = next((pid_names[p] for p in device_pids), "")

    per_op: dict[str, dict] = {}
    for e in events:
        args = e.get("args") or {}
        if e.get("ph") != "X" or e["pid"] not in device_pids or "device_duration_ps" not in args:
            continue
        if re.match(r"^(jit_|\d+$)", e["name"]) or e["name"].split(".")[0].endswith("-start"):
            continue
        row = per_op.setdefault(
            e["name"],
            {"name": e["name"], "category": args.get("hlo_category", e["name"]),
             "s": 0.0, "flops": 0.0, "bytes": 0.0,
             "source": args.get("source", "?"), "count": 0},
        )
        row["s"] += int(args["device_duration_ps"]) / 1e12
        row["flops"] += float(args.get("model_flops", 0) or 0)
        row["bytes"] += float(args.get("raw_bytes_accessed", 0) or 0)
        row["count"] += 1
    return device_name, list(per_op.values())


def roofline_report(
    trace_dir: str,
    peak_flops: float | None = None,
    peak_bw: float | None = None,
    steps: int = 1,
) -> dict:
    """Aggregate a :func:`trace` capture into a per-HLO-category roofline.

    Reads the Chrome-trace export ``jax.profiler`` writes, sums device
    op time / model FLOPs / bytes accessed by ``hlo_category``, and for
    each category reports achieved FLOP/s and bytes/s against the
    chip's compute and HBM roofs — the analysis the reference's
    TensorBoard profiler window left to the reader (SURVEY.md §5).

    Returns ``{"total_ms", "device": str, "categories": [{name, ms,
    tflops_per_s, gb_per_s, gb, bound, roofline_ms}, ...]}`` where
    ``bound`` is which roof the category sits under and ``roofline_ms``
    is the best-case time at 100% of that roof.
    """
    import collections

    device_name, rows = _device_op_rows(trace_dir)

    if peak_flops is None or peak_bw is None:
        # The chrome trace doesn't record the device *kind*, only
        # "/device:TPU:0" — so peaks come from the local backend. When
        # analyzing a trace on a different machine (or an unknown chip),
        # pass peak_flops/peak_bw explicitly.
        match = device_peaks()
        if match is None:
            log.warning(
                "roofline_report: unknown device kind %r — using conservative "
                "cpu peaks; pass peak_flops/peak_bw for a meaningful roofline",
                jax.devices()[0].device_kind,
            )
            match = _PEAKS["cpu"]
        peak_flops, peak_bw = peak_flops or match[0], peak_bw or match[1]

    by_cat = collections.defaultdict(lambda: [0.0, 0.0, 0.0])
    for r in rows:
        agg = by_cat[r["category"]]
        agg[0] += r["s"]
        agg[1] += r["flops"]
        agg[2] += r["bytes"]

    categories = []
    for cat, (dur, fl, by) in sorted(by_cat.items(), key=lambda kv: -kv[1][0]):
        if dur <= 0:
            continue
        flop_bound, byte_bound = fl / peak_flops, by / peak_bw
        categories.append(
            {
                "name": cat,
                "ms": dur * 1e3,
                "tflops_per_s": fl / dur / 1e12,
                "gb_per_s": by / dur / 1e9,
                "gb": by / 1e9,
                "bound": "compute" if flop_bound >= byte_bound else "memory",
                "roofline_ms": max(flop_bound, byte_bound) * 1e3,
            }
        )
    for c in categories:
        for k in ("ms", "gb", "roofline_ms"):
            c[k] /= steps
    total = sum(c["ms"] for c in categories)
    ideal = sum(c["roofline_ms"] for c in categories)
    return {
        "steps": steps,
        "total_ms": total,
        "roofline_ms": ideal,
        "roofline_fraction": ideal / total if total else 0.0,
        "device": device_name,
        "peak_tflops": peak_flops / 1e12,
        "peak_gbps": peak_bw / 1e9,
        "categories": categories,
    }


def print_roofline(report: dict) -> None:
    """Render :func:`roofline_report` as the table BENCHMARKS.md carries."""
    print(
        f"device {report['device']}  roofs: {report['peak_tflops']:.0f} TFLOP/s, "
        f"{report['peak_gbps']:.0f} GB/s"
    )
    print(f"{'category':26s}{'ms':>9s}{'TFLOP/s':>9s}{'GB/s':>7s}{'GB':>7s}  bound  best-case ms")
    for c in report["categories"]:
        print(
            f"{c['name']:26s}{c['ms']:9.2f}{c['tflops_per_s']:9.1f}{c['gb_per_s']:7.0f}"
            f"{c['gb']:7.2f}  {c['bound']:6s}{c['roofline_ms']:10.2f}"
        )
    print(
        f"total {report['total_ms']:.1f} ms vs roofline best-case {report['roofline_ms']:.1f} ms "
        f"-> running at {report['roofline_fraction'] * 100:.0f}% of the roofline bound"
    )


def top_ops(trace_dir: str, steps: int = 1, n: int = 15) -> list[dict]:
    """Per-op (not per-category) view of a :func:`trace` capture: the n
    heaviest device ops with duration, FLOP/s, bytes and source line —
    for pinpointing which op a bound category's time lives in.
    Durations/bytes are divided by ``steps``."""
    _, rows = _device_op_rows(trace_dir)
    out = sorted(rows, key=lambda r: -r["s"])[:n]
    result = []
    for r in out:
        ms = r["s"] * 1e3 / steps
        result.append(
            {
                "name": r["name"],
                "category": r["category"],
                "source": r["source"],
                "count": r["count"],
                "ms": ms,
                "gb": r["bytes"] / 1e9 / steps,
                "tflops_per_s": (r["flops"] / steps) / max(ms / 1e3, 1e-12) / 1e12,
            }
        )
    return result
