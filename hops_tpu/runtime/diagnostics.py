"""Tracing, hang detection, and determinism.

SURVEY.md §5 found the reference's story thin: TensorBoard profiling
only (``profile_batch='5,10'`` in Keras callbacks), **no race/deadlock
tooling**, and no deterministic mode. The TPU equivalents:

- :func:`trace` — ``jax.profiler`` trace into the active run's logdir,
  viewable in TensorBoard/XProf exactly where the reference's profiler
  window landed (reference: notebooks/ml/Experiment/Tensorflow/
  mnist.ipynb:172-173).
- :class:`Watchdog` — collective-deadlock detector. SPMD programs hang,
  not crash, when one host misses a collective; the watchdog fires when
  the step loop stops heartbeating, dumps every Python thread's stack,
  and optionally kills the process so the job scheduler can retry.
- :func:`deterministic_mode` — one switch for bitwise-reproducible runs
  (XLA deterministic ops + seeded ``jax.random`` keys), the stand-in
  for race detection on a platform where the compiler owns scheduling.
"""

from __future__ import annotations

import contextlib
import faulthandler
import os
import sys
import threading
import time
from typing import Iterator

import jax

from hops_tpu.runtime import rundir
from hops_tpu.runtime.logging import get_logger

log = get_logger(__name__)


@contextlib.contextmanager
def trace(logdir: str | None = None) -> Iterator[str]:
    """Capture a profiler trace for the with-block into ``logdir``
    (default: ``<active run>/trace``)."""
    target = logdir or os.path.join(rundir.logdir(), "trace")
    os.makedirs(target, exist_ok=True)
    jax.profiler.start_trace(target)
    try:
        yield target
    finally:
        jax.profiler.stop_trace()


class Watchdog:
    """Detects a stalled step loop (the usual face of a collective deadlock).

    The training loop calls :meth:`heartbeat` once per step; a daemon
    thread fires after ``timeout_s`` without one, logs every thread's
    stack (so the hung collective is visible in the trace), and calls
    ``on_hang`` — default: dump + ``os._exit(42)`` when ``fatal`` else
    just log, letting an external supervisor restart the host. This is
    the framework-level replacement for the failure detection the
    reference outsourced to YARN container restarts (SURVEY.md §5).
    """

    def __init__(self, timeout_s: float = 300.0, fatal: bool = False, on_hang=None):
        self.timeout_s = timeout_s
        self.fatal = fatal
        self.on_hang = on_hang
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._fired = False
        self._thread: threading.Thread | None = None

    def heartbeat(self) -> None:
        self._last = time.monotonic()

    @property
    def fired(self) -> bool:
        return self._fired

    def _watch(self) -> None:
        while not self._stop.wait(min(self.timeout_s / 4, 5.0)):
            if time.monotonic() - self._last > self.timeout_s:
                self._fired = True
                log.error(
                    "watchdog: no heartbeat for %.0fs — possible collective "
                    "deadlock; dumping thread stacks",
                    self.timeout_s,
                )
                faulthandler.dump_traceback(file=sys.stderr)
                if self.on_hang is not None:
                    self.on_hang()
                elif self.fatal:
                    os._exit(42)
                return

    def start(self) -> "Watchdog":
        self._last = time.monotonic()
        self._thread = threading.Thread(target=self._watch, daemon=True, name="hops-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


@contextlib.contextmanager
def deterministic_mode(seed: int = 0) -> Iterator[jax.Array]:
    """Bitwise-reproducible execution for the with-block.

    Yields a seeded root PRNG key. XLA scheduling on TPU is already
    deterministic for a fixed program; the remaining nondeterminism
    (autotuned reductions on other backends, Python hash order) is
    pinned here.
    """
    prev = jax.config.jax_default_prng_impl
    os.environ.setdefault("TF_DETERMINISTIC_OPS", "1")
    jax.config.update("jax_default_prng_impl", "threefry2x32")
    try:
        yield jax.random.PRNGKey(seed)
    finally:
        jax.config.update("jax_default_prng_impl", prev)
