"""Project-scoped filesystem façade.

Re-creates the surface of the reference's ``hops.hdfs`` module
(reference: notebooks/ml/Filesystem/HopsFSOperations.ipynb, SURVEY.md
§2.2) on top of a pluggable storage backend. The reference's backend was
HopsFS/HDFS reached through native libhdfs; here the default backend is
POSIX (which covers local disk and FUSE-mounted GCS buckets), with the
backend interface kept narrow so a native C++ driver (e.g. a direct GCS
client) can slot in.

Paths behave like the reference's: relative paths are resolved against
the *project* root inside the workspace, mirroring
``hdfs.project_path()``; absolute paths are taken as-is.
"""

from __future__ import annotations

import getpass
import json
import os
import pickle
import shutil
import stat as stat_mod
from pathlib import Path
from typing import Any

from hops_tpu.runtime import config

_WORKSPACE_ENV = "HOPS_TPU_WORKSPACE"


def workspace_root() -> Path:
    """Root of all projects (the reference's HopsFS root)."""
    ws = config.runtime().workspace or os.environ.get(_WORKSPACE_ENV, "")
    if not ws:
        ws = str(Path.home() / "hops_tpu_workspace")
    p = Path(ws)
    p.mkdir(parents=True, exist_ok=True)
    return p


def project_name() -> str:
    """Reference: ``hdfs.project_name()``."""
    return config.runtime().project


def project_user() -> str:
    """Reference: ``hdfs.project_user()`` (``<project>__<user>``)."""
    return f"{project_name()}__{getpass.getuser()}"


def project_path(rel: str = "") -> str:
    """Absolute path of ``rel`` inside the current project's dataset root.

    Reference: ``hdfs.project_path()`` in
    notebooks/ml/Experiment/Tensorflow/mnist.ipynb:70.
    """
    root = workspace_root() / project_name()
    root.mkdir(parents=True, exist_ok=True)
    return str(root / rel) if rel else str(root) + os.sep


def resolve(path: str | Path) -> Path:
    """Absolute workspace path: relative inputs anchor at the project
    root, absolute inputs pass through."""
    p = Path(path)
    return p if p.is_absolute() else Path(project_path(str(p)))


_abs = resolve  # internal alias used throughout this module


# -- basic ops (reference: HopsFSOperations.ipynb cells 3-19) ----------------


def exists(path: str | Path) -> bool:
    return _abs(path).exists()


def mkdir(path: str | Path) -> None:
    _abs(path).mkdir(parents=True, exist_ok=True)


def rmr(path: str | Path) -> None:
    """Recursive remove (reference: ``hdfs.rmr``)."""
    p = _abs(path)
    if p.is_dir() and not p.is_symlink():
        shutil.rmtree(p, ignore_errors=True)
    elif p.exists():
        p.unlink()


def cp(src: str | Path, dst: str | Path, overwrite: bool = True) -> None:
    s, d = _abs(src), _abs(dst)
    if d.is_dir():
        d = d / s.name
    if d.exists() and not overwrite:
        raise FileExistsError(str(d))
    d.parent.mkdir(parents=True, exist_ok=True)
    if s.is_dir():
        shutil.copytree(s, d, dirs_exist_ok=True)
    else:
        shutil.copy2(s, d)


def move(src: str | Path, dst: str | Path) -> None:
    s, d = _abs(src), _abs(dst)
    d.parent.mkdir(parents=True, exist_ok=True)
    shutil.move(str(s), str(d))


def rename(src: str | Path, dst: str | Path) -> None:
    move(src, dst)


def ls(path: str | Path = "", recursive: bool = False) -> list[str]:
    p = _abs(path)
    if recursive:
        return sorted(str(c) for c in p.rglob("*"))
    return sorted(str(c) for c in p.iterdir())


def glob(pattern: str) -> list[str]:
    """Glob within the project (reference: ``hdfs.glob``).

    Shell semantics: ``*`` does not cross ``/`` (use ``**`` to recurse).
    """
    return sorted(str(c) for c in Path(project_path()).glob(pattern))


def lsl(path: str | Path = "") -> list[dict[str, Any]]:
    """Detailed listing (reference: ``hdfs.lsl``)."""
    return [stat(c) for c in ls(path)]


def stat(path: str | Path) -> dict[str, Any]:
    st = _abs(path).stat()
    return {
        "path": str(_abs(path)),
        "size": st.st_size,
        "permission": stat_mod.filemode(st.st_mode),
        "owner": st.st_uid,
        "last_modified": st.st_mtime,
        "is_dir": _abs(path).is_dir(),
    }


def chmod(path: str | Path, mode: int) -> None:
    _abs(path).chmod(mode)


# -- data transfer (reference: copy_to_local / copy_to_hdfs) -----------------


def copy_to_local(path: str | Path, local_dir: str | Path = ".", overwrite: bool = True) -> str:
    """Stage a workspace file onto local disk (reference:
    ``hdfs.copy_to_local``, mnist.ipynb:77)."""
    src = _abs(path)
    dst = Path(local_dir) / src.name
    if dst.resolve() == src.resolve():
        return str(dst)
    if dst.exists() and not overwrite:
        raise FileExistsError(str(dst))
    if src.is_dir():
        shutil.copytree(src, dst, dirs_exist_ok=True)
    else:
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy2(src, dst)
    return str(dst)


def copy_to_workspace(local_path: str | Path, rel_dir: str = "", overwrite: bool = True) -> str:
    """Upload a local file into the project (reference: ``hdfs.copy_to_hdfs``)."""
    src = Path(local_path)
    dst_dir = Path(project_path(rel_dir))
    dst_dir.mkdir(parents=True, exist_ok=True)
    dst = dst_dir / src.name
    if dst.exists() and not overwrite:
        raise FileExistsError(str(dst))
    if src.is_dir():
        shutil.copytree(src, dst, dirs_exist_ok=True)
    else:
        shutil.copy2(src, dst)
    return str(dst)


# `copy_to_hdfs` kept as an alias so reference-shaped code ports 1:1.
copy_to_hdfs = copy_to_workspace


# -- (de)serialization (reference: hdfs.load / hdfs.dump) --------------------


def dump(data: Any, path: str | Path) -> str:
    """Write text/bytes/obj to a project path (reference: ``hdfs.dump``)."""
    p = _abs(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    if isinstance(data, bytes):
        p.write_bytes(data)
    elif isinstance(data, str):
        p.write_text(data)
    else:
        p.write_bytes(pickle.dumps(data))
    return str(p)


def load(path: str | Path) -> bytes:
    """Read raw bytes (reference: ``hdfs.load``)."""
    return _abs(path).read_bytes()


def load_json(path: str | Path) -> Any:
    return json.loads(_abs(path).read_text())


def dump_json(data: Any, path: str | Path) -> str:
    p = _abs(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(json.dumps(data, indent=2, default=str))
    return str(p)
