"""Deterministic fault injection: break the platform on purpose.

Every resilience behavior in this tree — checkpoint quarantine, the
``run_preemptible`` supervisor, serving load-shedding, trial retries —
is proven by *injecting the fault it defends against*, not by hoping a
flaky CI run exercises it. This module is the injection registry:
named **fault points** compiled into the hot paths, disarmed by
default (one ``is None`` check — see the ``bench.py --fault-overhead``
smoke), armed either in code::

    from hops_tpu.runtime import faultinject
    faultinject.arm(faultinject.FaultPlan.parse(
        "loader.read=error:OSError@times=1,after=5"))

or from the environment for end-to-end chaos tests::

    HOPS_TPU_FAULTS="checkpoint.save=corrupt@times=1;serving.handle=error:RuntimeError@p=0.5"

Grammar: ``point=mode[:arg][@key=val,...]`` joined by ``;``.
Modes: ``error[:ExcName]`` raises (builtin exception, default
``RuntimeError``), ``latency:seconds`` sleeps, ``corrupt`` asks the
fault point to damage its payload (bytes) or artifact (files) — points
that have nothing to damage ignore it, and ``partition`` black-holes
the passage (raises ``ConnectionError``, records a ``partition``
flight event — the network-partition simulator, normally armed at the
``transport.send`` point via :func:`cut`/:func:`heal`). Keys: ``p``
(probability, default 1), ``times`` (max firings, default unlimited),
``after`` (passages to skip first, default 0), ``seed``, and ``key`` —
a discriminator matched against the value the fault point passes to
``fire(point, key=...)``, so a fault can target ONE replica port or
ONE feature shard out of many sharing a process (gray failures are
per-component by nature; a keyed spec counts passages only for its
key, keeping replay deterministic per component).

Partitions are **directional**: ``transport.send`` evaluates a send
from ``src`` to ``dst`` against three keys — ``dst`` (anything → dst),
``src->dst`` (that edge only) and ``src->*`` (src's whole egress) — so
asymmetric cuts (A→B delivered while B→A is black-holed) are one keyed
clause each. ``dst`` is the logical host name when the endpoint was
registered via :func:`name_endpoint` (hostd names its own agent port
and every unit it spawns), else the raw ``host:port``. See
docs/operations.md "Partition tolerance & fencing".

Determinism: each spec keeps a passage counter; probabilistic firing
draws from ``random.Random((seed, point, passage))`` — a plan replays
identically across runs and regardless of thread interleaving *per
point* (passages are counted under a lock).

Fault points wired through the stack (keep in sync with
docs/operations.md "Failure handling & fault injection"):

==================  ========================================================
``checkpoint.save``     ``CheckpointManager.save`` (corrupt: damages the
                        step's files after its manifest is written)
``checkpoint.restore``  ``CheckpointManager.restore`` (corrupt: damages the
                        newest step before verification)
``loader.read``         ``LoaderIterator`` batch production
``serving.handle``      the serving POST handler, before predict
``search.trial``        ``TrialDriver._run_trial``, around the train fn
``pubsub.publish``      ``pubsub.Producer.send`` (corrupt: mangles the
                        encoded record)
``pubsub.poll``         ``pubsub.Consumer.poll_records``, per record
                        (error/latency abort the poll with the offset
                        restored — a retry re-delivers the batch;
                        corrupt mangles the record consumer-side into
                        a poison record, the durable topic untouched)
``lm_engine.dispatch``  ``LMEngine.step``, before the iteration's device
                        dispatch wave (an error fails only the in-flight
                        requests; the scheduler keeps serving)
``online.lookup``       ``ShardedOnlineStore.multi_get``, per shard batch
                        (an error degrades those keys to the missing-key
                        policy and feeds the shard's breaker)
``online.materialize``  the write-through ``Materializer`` poll/flush
                        cycle (survived with backoff; freshness lag
                        rises while it stalls)
``router.forward``      the fleet router, before forwarding a request
                        to its chosen replica (latency delays the hop;
                        an error is treated as a replica failure and
                        the request retries on another replica)
``router.scrape``       the router's per-replica ``/metrics.json``
                        scrape, keyed by replica port (latency models
                        a gray metrics path: the scrape times out, the
                        view goes stale and the replica is
                        deprioritized, routing never stalls)
``shard.lookup``        one shard-lookup *attempt* inside
                        ``ShardedOnlineStore.multi_get``'s parallel
                        fan-out, keyed by shard index (latency models
                        a slow-but-alive shard: the per-shard hedge
                        and the multi-get deadline contain it)
``fleet.spawn``         ``ReplicaManager.spawn``, before a replica
                        worker is created (an error fails that spawn
                        attempt; autoscaler/rollout retry policies own
                        the recovery)
``placement.rpc``       every placement control-plane RPC, keyed by
                        host name — client-side in
                        ``PlacementClient._rpc`` (a partition: the
                        verb never reaches the host) and agent-side in
                        the hostd dispatcher. The per-host breaker
                        ejects the partitioned host; spawns re-place
                        on survivors
``transport.send``      every ``HTTPPool`` exchange, evaluated by
                        :func:`fire_transport` against the directional
                        keys above before any bytes move — the network
                        fabric itself. ``partition`` black-holes the
                        send (the classic cut), ``latency`` models a
                        slow link. Also fired by hostd's heartbeat
                        announce (``dst=registry``) so a cut host's
                        lease expires and it self-fences
==================  ========================================================
"""

from __future__ import annotations

import builtins
import dataclasses
import hashlib
import os
import random
import threading
import time
from pathlib import Path
from typing import Any

from hops_tpu.runtime import flight
from hops_tpu.runtime.logging import get_logger
from hops_tpu.telemetry import tracing
from hops_tpu.telemetry.metrics import REGISTRY

log = get_logger(__name__)

ENV_VAR = "HOPS_TPU_FAULTS"

#: The named injection points compiled into the stack.
POINTS = (
    "checkpoint.save",
    "checkpoint.restore",
    "loader.read",
    "serving.handle",
    "search.trial",
    "pubsub.publish",
    "pubsub.poll",
    "lm_engine.dispatch",
    "online.lookup",
    "online.materialize",
    "router.forward",
    "router.scrape",
    "shard.lookup",
    "fleet.spawn",
    "placement.rpc",
    "serving.start",
    "workload.publish",
    "transport.send",
)

_MODES = ("error", "latency", "corrupt", "partition")

_m_injected = REGISTRY.counter(
    "hops_tpu_faults_injected_total",
    "Faults actually injected, per fault point and mode",
    labels=("point", "mode"),
)


class FaultPlanError(ValueError):
    """A ``HOPS_TPU_FAULTS`` string / FaultSpec that doesn't parse."""


@dataclasses.dataclass
class FaultSpec:
    """One armed fault: what to do at a point, and on which passages."""

    point: str
    mode: str
    arg: Any = None  # exception class (error) / seconds (latency)
    probability: float = 1.0
    times: int | None = None
    after: int = 0
    seed: int = 0
    #: Optional discriminator: the spec fires only on passages whose
    #: ``fire(point, key=...)`` value equals it (replica port, shard
    #: index). None matches every passage.
    key: str | None = None
    # runtime counters — guarded by: FaultPlan._lock
    passages: int = 0
    fired: int = 0

    def __post_init__(self) -> None:
        if self.point not in POINTS:
            raise FaultPlanError(
                f"unknown fault point {self.point!r}; known: {', '.join(POINTS)}")
        if self.mode not in _MODES:
            raise FaultPlanError(
                f"unknown fault mode {self.mode!r}; known: {', '.join(_MODES)}")
        if self.mode == "error":
            if self.arg is None:
                self.arg = RuntimeError
            elif isinstance(self.arg, str):
                exc = getattr(builtins, self.arg, None)
                if not (isinstance(exc, type) and issubclass(exc, BaseException)):
                    raise FaultPlanError(
                        f"{self.arg!r} is not a builtin exception type")
                self.arg = exc
        elif self.mode == "latency":
            try:
                self.arg = float(self.arg)
            except (TypeError, ValueError):
                raise FaultPlanError(
                    f"latency mode needs seconds, got {self.arg!r}") from None
        elif self.mode == "partition" and self.arg is not None:
            raise FaultPlanError(
                f"partition mode takes no argument, got {self.arg!r}")
        if not 0.0 <= self.probability <= 1.0:
            raise FaultPlanError(f"probability must be in [0,1], got "
                                 f"{self.probability}")

    def _should_fire(self) -> bool:  # guarded by: FaultPlan._lock
        passage = self.passages
        self.passages += 1
        if passage < self.after:
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        if self.probability < 1.0:
            # Stable digest seed: random.seed rejects tuples on 3.11+
            # and would hash the point name under PYTHONHASHSEED on
            # 3.10 — either way breaking cross-run replayability.
            digest = hashlib.sha256(
                f"{self.seed}:{self.point}:{passage}".encode()).digest()
            rng = random.Random(int.from_bytes(digest[:8], "big"))
            if rng.random() >= self.probability:
                return False
        self.fired += 1
        return True


class FaultPlan:
    """An armed set of :class:`FaultSpec`, indexed by point."""

    def __init__(self, specs: list[FaultSpec]):
        self._lock = threading.Lock()
        self._by_point: dict[str, list[FaultSpec]] = {}
        for spec in specs:
            self._by_point.setdefault(spec.point, []).append(spec)

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        """Parse the ``HOPS_TPU_FAULTS`` grammar (see module docstring)."""
        specs: list[FaultSpec] = []
        for clause in text.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            if "=" not in clause:
                raise FaultPlanError(f"expected point=mode[...], got {clause!r}")
            point, rest = clause.split("=", 1)
            opts = ""
            if "@" in rest:
                rest, opts = rest.split("@", 1)
            mode, _, arg = rest.partition(":")
            kwargs: dict[str, Any] = {}
            for kv in opts.split(","):
                kv = kv.strip()
                if not kv:
                    continue
                if "=" not in kv:
                    raise FaultPlanError(f"expected key=val in options, got {kv!r}")
                k, v = kv.split("=", 1)
                k = k.strip()
                if k == "p":
                    kwargs["probability"] = float(v)
                elif k in ("times", "after", "seed"):
                    kwargs[k] = int(v)
                elif k == "key":
                    kwargs["key"] = v.strip()
                else:
                    raise FaultPlanError(f"unknown fault option {k!r}")
            specs.append(FaultSpec(point=point.strip(), mode=mode.strip(),
                                   arg=arg or None, **kwargs))
        if not specs:
            raise FaultPlanError(f"no fault specs in {text!r}")
        return cls(specs)

    def evaluate(self, point: str, key: str | None = None, *,
                 keyed_only: bool = False) -> list[FaultSpec]:
        """The specs that fire on this passage of ``point``. A keyed
        spec sees (and counts) only passages carrying its key, so its
        ``times``/``after``/``p`` schedule replays deterministically
        per component regardless of how other keys interleave.
        ``keyed_only`` skips key-less specs — :func:`fire_transport`
        evaluates several directional keys per send and must count an
        unkeyed spec's passage exactly once."""
        with self._lock:
            specs = self._by_point.get(point)
            if not specs:
                return []
            return [
                s for s in specs
                if (s.key == key if keyed_only or s.key is not None else True)
                and s._should_fire()
            ]

    def add(self, spec: FaultSpec) -> None:
        """Arm one more spec in a live plan (:func:`cut` uses this to
        open partitions mid-run without disturbing armed schedules)."""
        with self._lock:
            self._by_point.setdefault(spec.point, []).append(spec)

    def remove(self, *, point: str | None = None, mode: str | None = None,
               key: str | None = None) -> int:
        """Drop armed specs matching every given filter; returns the
        count removed (:func:`heal` closes partitions with this)."""
        removed = 0
        with self._lock:
            for pt in list(self._by_point):
                if point is not None and pt != point:
                    continue
                keep = [
                    s for s in self._by_point[pt]
                    if not ((mode is None or s.mode == mode)
                            and (key is None or s.key == key))
                ]
                removed += len(self._by_point[pt]) - len(keep)
                if keep:
                    self._by_point[pt] = keep
                else:
                    del self._by_point[pt]
        return removed

    def describe(self) -> str:
        with self._lock:
            return "; ".join(
                f"{s.point}={s.mode}"
                + (f":{getattr(s.arg, '__name__', s.arg)}" if s.arg is not None else "")
                + (f"@key={s.key}" if s.key is not None else "")
                for specs in self._by_point.values() for s in specs
            )


#: The armed plan. ``None`` = disarmed: :func:`fire` is a single
#: attribute load + ``is None`` test, nothing else (bench-guarded).
_PLAN: FaultPlan | None = None


def arm(plan: FaultPlan | str) -> FaultPlan:
    """Arm a plan (or a plan string) process-wide; returns it."""
    global _PLAN
    if isinstance(plan, str):
        plan = FaultPlan.parse(plan)
    _PLAN = plan
    log.warning("fault injection ARMED: %s", plan.describe())
    return plan


def disarm() -> None:
    global _PLAN
    _PLAN = None


def armed() -> bool:
    return _PLAN is not None


def arm_from_env(environ: dict | None = None) -> FaultPlan | None:
    """Arm from ``HOPS_TPU_FAULTS`` if set (e2e chaos tests); returns
    the plan or None. Malformed plans raise — a chaos test that thinks
    it is injecting faults but isn't must not pass silently."""
    text = (environ if environ is not None else os.environ).get(ENV_VAR)
    if not text:
        return None
    return arm(text)


def _apply(spec: FaultSpec, point: str, **info: Any) -> bool:
    """Execute one fired spec; returns True when it was ``corrupt``.
    ``info`` rides into the flight event (``src``/``dst`` for
    transport passages)."""
    _m_injected.inc(point=point, mode=spec.mode)
    # The black box + the causal thread: a fired fault lands in the
    # flight recorder and annotates whatever request trace it fired
    # under, so post-incident the injected failure, the retry it
    # provoked, and the breaker it tripped read in one sequence.
    # Partitions get their own flight kind: a chaos drill's timeline
    # (cut → fence → re-place → heal → generation_rejected) must read
    # from the recorder without grepping generic fault noise.
    kind = "partition" if spec.mode == "partition" else "fault_fired"
    flight.record(kind, point=point, mode=spec.mode, **info)
    tracing.add_event(kind, point=point, mode=spec.mode, **info)
    if spec.mode == "latency":
        log.warning("faultinject: %s sleeping %.3fs", point, spec.arg)
        time.sleep(spec.arg)
        return False
    if spec.mode == "error":
        log.warning("faultinject: %s raising %s", point, spec.arg.__name__)
        raise spec.arg(f"faultinject: injected {spec.arg.__name__} at {point}")
    if spec.mode == "partition":
        where = (f"{info.get('src')}->{info.get('dst')}"
                 if "dst" in info else point)
        log.warning("faultinject: partition black-holed %s", where)
        raise ConnectionError(f"faultinject: partition at {where} (black-holed)")
    log.warning("faultinject: %s corrupt trigger", point)
    return True


def fire(point: str, key: Any = None) -> bool:
    """Evaluate ``point``. Raises / sleeps per the armed plan; returns
    True when a ``corrupt`` spec fired (the site decides what that
    means for its artifact). Disarmed: returns False immediately.
    ``key`` names the specific component this passage belongs to
    (replica port, shard index) for ``@key=``-scoped specs."""
    if _PLAN is None:
        return False
    corrupt = False
    for spec in _PLAN.evaluate(point, key=None if key is None else str(key)):
        corrupt |= _apply(spec, point)
    return corrupt


def fire_data(point: str, data: bytes) -> bytes:
    """Like :func:`fire` for byte-payload points: a ``corrupt`` spec
    returns a damaged copy of ``data`` instead of a flag."""
    if _PLAN is None:
        return data
    if fire(point):
        return _corrupt_bytes(data)
    return data


# ---------------------------------------------------------------- partitions
#
# The network-partition simulator. HTTPPool calls fire_transport()
# before every exchange; a ``partition`` spec at ``transport.send``
# black-holes matching sends with ConnectionError — exactly what a
# dropped SYN looks like to the caller, so every breaker/retry/hedge
# path exercises its real partition behavior. Cuts are directional
# (see the module docstring) and deterministic: FaultSpec's
# seed/p/times/after schedule applies per key.

_endpoints_lock = threading.Lock()
#: ``"host:port"`` → logical name, so chaos plans address hosts by the
#: names operators know (``key=h1``), not ephemeral ports.
_ENDPOINTS: dict[str, str] = {}


def name_endpoint(hostport: str, name: str) -> None:
    """Register ``host:port`` under a logical host name for partition
    keying. Hostd registers its agent port and every unit it spawns,
    so ``cut("h1")`` severs the whole host — agent and units alike."""
    with _endpoints_lock:
        _ENDPOINTS[hostport] = name


def endpoint_name(hostport: str) -> str:
    """The logical name for ``host:port`` (itself when unregistered)."""
    with _endpoints_lock:
        return _ENDPOINTS.get(hostport, hostport)


def fire_transport(src: str, dst: str) -> None:
    """Transport fault point: evaluate one send from the pool named
    ``src`` to endpoint ``dst`` (``host:port`` or a logical name).
    Matches specs keyed ``dst``, ``src->dst`` and ``src->*`` — plus
    unkeyed ``transport.send`` specs, counted exactly once per send.
    Raises ``ConnectionError`` on a fired partition; disarmed it is
    one attribute load + ``is None`` test."""
    plan = _PLAN
    if plan is None:
        return
    dname = endpoint_name(dst)
    fired = plan.evaluate("transport.send", key=dname)
    for key in (f"{src}->{dname}", f"{src}->*"):
        fired += plan.evaluate("transport.send", key=key, keyed_only=True)
    for spec in fired:
        _apply(spec, "transport.send", src=src, dst=dname)


def cut(key: str, *, probability: float = 1.0, times: int | None = None,
        after: int = 0, seed: int = 0) -> FaultSpec:
    """Open a partition: black-hole ``transport.send`` passages
    matching ``key`` (a destination name, ``src->dst`` edge, or
    ``src->*`` egress). Arms an empty plan if none is armed; adds to
    the live plan otherwise. Returns the armed spec; close the cut
    with :func:`heal`."""
    global _PLAN
    spec = FaultSpec(point="transport.send", mode="partition",
                     probability=probability, times=times, after=after,
                     seed=seed, key=key)
    plan = _PLAN
    if plan is None:
        plan = _PLAN = FaultPlan([])
    plan.add(spec)
    flight.record("partition", action="cut", key=key)
    log.warning("faultinject: partition CUT %s", key)
    return spec


def heal(key: str | None = None) -> int:
    """Close partitions: remove armed ``partition`` specs at
    ``transport.send`` matching ``key`` (all of them when None).
    Returns the number healed."""
    plan = _PLAN
    if plan is None:
        return 0
    healed = plan.remove(point="transport.send", mode="partition", key=key)
    if healed:
        flight.record("partition", action="heal", key=key or "*")
        log.warning("faultinject: partition HEALED %s (%d cut%s)",
                    key or "*", healed, "s" if healed != 1 else "")
    return healed


def _corrupt_bytes(data: bytes) -> bytes:
    """Deterministic damage: truncate the body to half and flip its
    first byte — enough to defeat checksums and parsers. A trailing
    newline is PRESERVED: line-framed payloads (pubsub records) must
    stay one damaged record, not bleed into the next line — a missing
    terminator would wedge tailing consumers on a partial-write check
    forever, which is a different fault than corruption."""
    tail = b"\n" if data.endswith(b"\n") else b""
    body = data[: len(data) - len(tail)]
    half = body[: max(1, len(body) // 2)]
    return bytes([half[0] ^ 0xFF]) + half[1:] + tail if half else tail


def corrupt_directory(directory: str | Path) -> Path | None:
    """Damage the largest file under ``directory`` in place (truncate
    to half) — the checkpoint fault points' artifact corruption.
    Returns the damaged path (None when the dir holds no files)."""
    directory = Path(directory)
    files = sorted(
        (p for p in directory.rglob("*") if p.is_file()),
        key=lambda p: p.stat().st_size,
    )
    if not files:
        return None
    victim = files[-1]
    data = victim.read_bytes()
    victim.write_bytes(_corrupt_bytes(data) if data else b"")
    log.warning("faultinject: corrupted %s (%d -> %d bytes)",
                victim, len(data), victim.stat().st_size)
    return victim


# E2E chaos tests arm via the environment before the process starts.
if os.environ.get(ENV_VAR):
    arm_from_env()
