"""TPU slice topology discovery.

The TPU-native replacement for the reference's ``hops.devices`` module,
which reported "number of GPUs accessible by the container" per Spark
executor (reference: notebooks/ml/Benchmarks/benchmark.ipynb cell 2,
SURVEY.md §2.2). On TPU the analogous questions are richer: how many
chips, how many hosts, what mesh shapes does the ICI fabric support,
which chips are local to this process. Everything here is derived from
``jax.devices()`` so it works identically on a real slice and on a
``--xla_force_host_platform_device_count`` fake mesh.
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Any

import jax
import numpy as np


@dataclasses.dataclass(frozen=True)
class SliceTopology:
    """Static description of the accelerator slice this program runs on."""

    platform: str
    num_chips: int
    num_hosts: int
    chips_per_host: int
    process_index: int
    device_kind: str
    # Physical ICI coords per chip (if exposed by the platform), else a
    # synthetic 1-D enumeration.
    coords: tuple[tuple[int, ...], ...]

    @property
    def is_multi_host(self) -> bool:
        return self.num_hosts > 1

    def mesh_shape(self, num_axes: int = 2) -> tuple[int, ...]:
        """A near-square factorization of ``num_chips`` into ``num_axes``.

        Used as the default mesh when the user does not specify one: on a
        v5e-16 ``mesh_shape(2) == (4, 4)``; on 8 fake CPU devices
        ``(4, 2)``.
        """
        shape = [1] * num_axes
        n = self.num_chips
        axis = 0
        while n > 1:
            # Peel the largest factor <= sqrt for balance.
            f = _largest_factor_leq(n, int(math.isqrt(n))) if axis < num_axes - 1 else n
            shape[axis] = f
            n //= f
            axis += 1
            if axis >= num_axes:
                shape[-1] *= n
                break
        return tuple(sorted(shape, reverse=True))


def _largest_factor_leq(n: int, bound: int) -> int:
    for f in range(max(bound, 1), 0, -1):
        if n % f == 0:
            return f
    return 1


def _device_coords(d: Any, fallback: int) -> tuple[int, ...]:
    coords = getattr(d, "coords", None)
    if coords is not None:
        return tuple(int(c) for c in coords)
    return (int(fallback),)


def topology() -> SliceTopology:
    """Discover the current slice topology from the JAX runtime."""
    devs = jax.devices()
    return SliceTopology(
        platform=devs[0].platform,
        num_chips=len(devs),
        num_hosts=jax.process_count(),
        chips_per_host=jax.local_device_count(),
        process_index=jax.process_index(),
        device_kind=devs[0].device_kind,
        coords=tuple(_device_coords(d, i) for i, d in enumerate(devs)),
    )


def get_num_chips() -> int:
    """Chips visible to the whole program (reference: ``devices.get_num_gpus``)."""
    return jax.device_count()


def get_num_local_chips() -> int:
    """Chips attached to this host/process."""
    return jax.local_device_count()


def num_hosts() -> int:
    """Host count — replaces the reference's ``util.num_executors()``
    (reference: notebooks/ml/Inference/Batch_Inference_Imagenet_Spark.ipynb:325)."""
    return jax.process_count()


def is_tpu() -> bool:
    return jax.devices()[0].platform in ("tpu", "axon")


def visible_devices() -> list[Any]:
    return list(jax.devices())


def fake_mesh_env(n: int = 8) -> dict[str, str]:
    """Env vars that emulate an ``n``-chip slice on CPU (SURVEY.md §4.4).

    Must be applied before JAX initializes a backend; used by the test
    suite's conftest and by subprocess-based trial executors. If jax was
    already imported (e.g. by a sitecustomize), additionally call
    ``jax.config.update("jax_platforms", "cpu")`` — the env var alone is
    snapshotted at import time.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    return {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"{flags} --xla_force_host_platform_device_count={n}".strip(),
    }


def device_matrix() -> np.ndarray:
    """Devices arranged [host, local_chip] — the physical layout meshes
    should respect so data-parallel collectives ride ICI, not DCN."""
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    return np.array(devs).reshape(jax.process_count(), -1)
