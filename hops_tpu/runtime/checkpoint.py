"""Sharded async checkpoint / resume — the durability layer.

The reference delegates checkpointing to framework callbacks writing
into the run's logdir (``ModelCheckpoint(filepath=logdir)``,
``torch.save`` — SURVEY.md §5 "Checkpoint / resume") and has **no
auto-resume of a killed run**. This module closes that gap the TPU way:

- orbax-backed **async** saves: the train loop hands off device arrays
  and keeps stepping while the write to the Experiments dataset happens
  in the background;
- **sharding-aware restore**: arrays come back with the same
  ``NamedSharding`` they were saved under (or any new mesh layout the
  caller requests via the template), so a run can resume on a
  differently-sized slice;
- ``restore_or_init`` — the one-call auto-resume the reference lacked.

Default directory is the active run's ``checkpoints/`` subdir, so the
reference's "durability = logdir synced to the Experiments dataset"
story carries over unchanged.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp

from hops_tpu.runtime import rundir
from hops_tpu.runtime.logging import get_logger

log = get_logger(__name__)


def _default_dir() -> str:
    stack = rundir._active.get()
    if stack:
        return stack[-1].checkpoint_dir
    return str(Path(rundir.logdir()) / "checkpoints")


def default_directory() -> str:
    """The directory a ``CheckpointManager()`` with no argument uses:
    the active run's ``checkpoints/`` subdir (or the logdir fallback)."""
    return _default_dir()


# -- data-state sidecars ------------------------------------------------------
#
# Input-pipeline iterator state (epoch, shard cursor, seed — see
# featurestore/loader.py) is a tiny JSON-able dict, not a sharded array
# pytree; storing it INSIDE the orbax tree would change the checkpoint
# structure for every restore template that predates it. It rides
# alongside instead: one small JSON file per checkpointed step, written
# atomically, so `run_preemptible` can resume the exact batch stream.


def _data_state_path(directory: str | Path, step: int) -> Path:
    return Path(directory) / f"data_state_{int(step)}.json"


def save_data_state(directory: str | Path | None, step: int, state: dict) -> None:
    """Persist an input-pipeline snapshot next to checkpoint ``step``."""
    import json
    import os

    path = _data_state_path(directory or _default_dir(), step)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_suffix(".json.tmp")
    tmp.write_text(json.dumps(state))
    os.replace(tmp, path)


def load_data_state(directory: str | Path | None, step: int) -> dict | None:
    """The input-pipeline snapshot saved with checkpoint ``step``, or
    None if that step carries no data state (pre-loader checkpoints)."""
    import json

    path = _data_state_path(directory or _default_dir(), step)
    try:
        return json.loads(path.read_text())
    except (OSError, ValueError):
        return None


def abstract_state(state: Any) -> Any:
    """Shape/dtype/sharding skeleton of a pytree, for targeted restore."""

    def _abs(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        return x

    return jax.tree.map(_abs, state)


class CheckpointManager:
    """Versioned checkpoints of a train-state pytree under one directory.

    ``async_save=True`` (default) returns from :meth:`save` as soon as
    the arrays are snapshotted off the device; call :meth:`wait` (or
    :meth:`close`) before reading the files back.
    """

    def __init__(
        self,
        directory: str | Path | None = None,
        max_to_keep: int = 3,
        async_save: bool = True,
        save_interval_steps: int = 1,
    ):
        self.directory = Path(directory or _default_dir()).resolve()
        self.directory.mkdir(parents=True, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save,
                save_interval_steps=save_interval_steps,
            ),
        )

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        return self._mgr.save(int(step), args=ocp.args.StandardSave(state), force=force)

    def save_data_state(self, step: int, state: dict) -> None:
        """Sidecar snapshot of input-pipeline state for ``step`` (see
        :func:`save_data_state`). Sidecars whose checkpoint step orbax
        has pruned (``max_to_keep``) are unlinked here — they no longer
        correspond to any restorable step and would otherwise
        accumulate one file per save forever."""
        save_data_state(self.directory, step, state)
        keep = set(self.all_steps())
        keep.add(int(step))  # an async save may not be finalized yet
        for p in self.directory.glob("data_state_*.json"):
            try:
                s = int(p.stem.rsplit("_", 1)[-1])
            except ValueError:
                continue
            if s not in keep:
                try:
                    p.unlink()
                except FileNotFoundError:
                    pass

    def load_data_state(self, step: int) -> dict | None:
        return load_data_state(self.directory, step)

    def restore(self, state_template: Any, step: int | None = None) -> Any:
        """Restore into the template's shapes/dtypes/shardings.

        ``state_template`` may be a concrete pytree (its arrays are used
        as placement spec) or the result of :func:`abstract_state`.
        """
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        template = abstract_state(state_template)
        return self._mgr.restore(int(step), args=ocp.args.StandardRestore(template))

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        return list(self._mgr.all_steps())

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def restore_or_init(state: Any, directory: str | Path | None = None) -> tuple[Any, int]:
    """Auto-resume: latest checkpoint if one exists, else ``state`` as-is.

    Returns ``(state, next_step)`` — the step to continue from (0 for a
    fresh run). The wrapper-function pattern stays a straight line:

        state = create_train_state(...)
        state, start = checkpoint.restore_or_init(state)
        for step in range(start, num_steps): ...
    """
    with CheckpointManager(directory, async_save=False) as mgr:
        step = mgr.latest_step()
        if step is None:
            return state, 0
        restored = mgr.restore(state, step)
        log.info("resumed from checkpoint step=%d dir=%s", step, mgr.directory)
        return restored, step + 1
