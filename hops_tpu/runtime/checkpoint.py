"""Sharded async checkpoint / resume — the durability layer.

The reference delegates checkpointing to framework callbacks writing
into the run's logdir (``ModelCheckpoint(filepath=logdir)``,
``torch.save`` — SURVEY.md §5 "Checkpoint / resume") and has **no
auto-resume of a killed run**. This module closes that gap the TPU way:

- orbax-backed **async** saves: the train loop hands off device arrays
  and keeps stepping while the write to the Experiments dataset happens
  in the background;
- **sharding-aware restore**: arrays come back with the same
  ``NamedSharding`` they were saved under (or any new mesh layout the
  caller requests via the template), so a run can resume on a
  differently-sized slice;
- ``restore_or_init`` — the one-call auto-resume the reference lacked;
- **integrity manifests** — every finalized step gets a
  ``manifest_<step>.json`` sidecar with per-file sizes and SHA-256
  checksums. Restore verifies the candidate step against its manifest
  first; a corrupt or partial step (truncated write, bitrot, a
  preemption mid-finalize) is **quarantined** — renamed to
  ``corrupt_<step>.quarantined``, preserved for forensics, invisible
  to orbax — and restore falls back to the newest *valid* step instead
  of crashing the resume path.

Default directory is the active run's ``checkpoints/`` subdir, so the
reference's "durability = logdir synced to the Experiments dataset"
story carries over unchanged.
"""

from __future__ import annotations

import hashlib
import json
import os
from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp

from hops_tpu.runtime import faultinject, flight, rundir
from hops_tpu.runtime.logging import get_logger
from hops_tpu.telemetry.metrics import REGISTRY

log = get_logger(__name__)

_m_quarantined = REGISTRY.counter(
    "hops_tpu_checkpoint_quarantined_total",
    "Checkpoint steps quarantined as corrupt/partial at restore time",
)


class CheckpointCorruptError(RuntimeError):
    """An explicitly requested step failed integrity verification."""


def _file_sha256(path: Path, chunk: int = 1 << 20) -> str:
    """Streaming digest: checkpoint shards are multi-GB on real pods —
    reading one whole into host memory per save/restore would spike
    RSS by the largest shard."""
    h = hashlib.sha256()
    with path.open("rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                return h.hexdigest()
            h.update(block)


def _default_dir() -> str:
    stack = rundir._active.get()
    if stack:
        return stack[-1].checkpoint_dir
    return str(Path(rundir.logdir()) / "checkpoints")


def default_directory() -> str:
    """The directory a ``CheckpointManager()`` with no argument uses:
    the active run's ``checkpoints/`` subdir (or the logdir fallback)."""
    return _default_dir()


# -- data-state sidecars ------------------------------------------------------
#
# Input-pipeline iterator state (epoch, shard cursor, seed — see
# featurestore/loader.py) is a tiny JSON-able dict, not a sharded array
# pytree; storing it INSIDE the orbax tree would change the checkpoint
# structure for every restore template that predates it. It rides
# alongside instead: one small JSON file per checkpointed step, written
# atomically, so `run_preemptible` can resume the exact batch stream.


def _data_state_path(directory: str | Path, step: int) -> Path:
    return Path(directory) / f"data_state_{int(step)}.json"


def save_data_state(directory: str | Path | None, step: int, state: dict) -> None:
    """Persist an input-pipeline snapshot next to checkpoint ``step``."""
    path = _data_state_path(directory or _default_dir(), step)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_suffix(".json.tmp")
    tmp.write_text(json.dumps(state))
    os.replace(tmp, path)


def load_data_state(directory: str | Path | None, step: int) -> dict | None:
    """The input-pipeline snapshot saved with checkpoint ``step``, or
    None if that step carries no data state (pre-loader checkpoints)."""
    path = _data_state_path(directory or _default_dir(), step)
    try:
        return json.loads(path.read_text())
    except FileNotFoundError:
        return None  # the normal pre-loader / no-sidecar case
    except (OSError, ValueError) as e:
        # A sidecar that EXISTS but won't load means the resume will
        # silently start from the wrong input position — at least make
        # that diagnosable.
        log.warning("data-state sidecar %s unreadable (%s: %s); resuming "
                    "without input-pipeline position", path,
                    type(e).__name__, e)
        return None


def abstract_state(state: Any) -> Any:
    """Shape/dtype/sharding skeleton of a pytree, for targeted restore."""

    def _abs(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        return x

    return jax.tree.map(_abs, state)


class CheckpointManager:
    """Versioned checkpoints of a train-state pytree under one directory.

    ``async_save=True`` (default) returns from :meth:`save` as soon as
    the arrays are snapshotted off the device; call :meth:`wait` (or
    :meth:`close`) before reading the files back.
    """

    def __init__(
        self,
        directory: str | Path | None = None,
        max_to_keep: int = 3,
        async_save: bool = True,
        save_interval_steps: int = 1,
    ):
        self.directory = Path(directory or _default_dir()).resolve()
        self.directory.mkdir(parents=True, exist_ok=True)
        self._async = async_save
        self._pending_manifests: set[int] = set()
        self._corrupt_steps: set[int] = set()  # faultinject.checkpoint.save
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save,
                save_interval_steps=save_interval_steps,
            ),
        )

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        saved = self._mgr.save(int(step), args=ocp.args.StandardSave(state), force=force)
        if saved:
            # Fault point fires on ACTUAL saves (orbax declines
            # off-interval steps), so a plan's passage schedule counts
            # checkpoints, not loop iterations. Corrupt mode damages
            # THIS step's files once its manifest is written
            # (post-finalize bitrot — the manifest records healthy
            # checksums, so restore must catch the mismatch).
            if faultinject.fire("checkpoint.save"):
                self._corrupt_steps.add(int(step))
            self._pending_manifests.add(int(step))
        # Orbax serializes saves: by the time save() returns, every
        # EARLIER step is finalized on disk and safe to checksum. The
        # current step joins them once it finalizes (next save / wait).
        # Declined off-interval saves with nothing pending skip the
        # flush entirely — run_preemptible calls save() every training
        # step, and the flush's step scan + manifest glob is remote
        # LIST traffic on GCS/NFS checkpoint dirs.
        if saved or self._pending_manifests:
            self._flush_manifests(exclude=int(step) if self._async else None)
        return saved

    # -- integrity manifests --------------------------------------------------

    def _manifest_path(self, step: int) -> Path:
        return self.directory / f"manifest_{int(step)}.json"

    def _step_dir(self, step: int) -> Path:
        return self.directory / str(int(step))

    def _flush_manifests(self, exclude: int | None = None) -> None:
        for step in sorted(self._pending_manifests):
            if step == exclude:
                continue
            if self._write_manifest(step):
                self._pending_manifests.discard(step)
        # GC manifests whose step orbax has pruned (same rationale as
        # the data-state sidecar GC in save_data_state).
        keep = set(self.all_steps()) | self._pending_manifests
        if exclude is not None:
            keep.add(exclude)
        for p in self.directory.glob("manifest_*.json"):
            try:
                s = int(p.stem.rsplit("_", 1)[-1])
            except ValueError:
                continue
            if s not in keep:
                try:
                    p.unlink()
                except OSError as e:
                    log.warning("manifest GC could not remove %s: %s", p, e)

    def _write_manifest(self, step: int) -> bool:
        """Checksum a finalized step into its manifest. Returns True
        when the step no longer needs one (written, or pruned) — an
        async step still writing to its orbax temp dir returns False
        and stays pending until the next flush."""
        step_dir = self._step_dir(step)
        if not step_dir.is_dir():
            # Either pruned (max_to_keep — done with it) or an async
            # save not yet finalized into place (keep waiting).
            return step not in self.all_steps()
        files = {}
        for p in sorted(step_dir.rglob("*")):
            if not p.is_file():
                continue
            files[p.relative_to(step_dir).as_posix()] = {
                "size": p.stat().st_size,
                "sha256": _file_sha256(p),
            }
        tmp = self._manifest_path(step).with_suffix(".json.tmp")
        tmp.write_text(json.dumps({"step": int(step), "files": files}))
        os.replace(tmp, self._manifest_path(step))
        if step in self._corrupt_steps:  # armed fault: post-manifest bitrot
            self._corrupt_steps.discard(step)
            faultinject.corrupt_directory(step_dir)
        return True

    def verify_step(self, step: int) -> str | None:
        """Integrity-check ``step`` against its manifest. Returns None
        when it passes (or predates manifests — nothing to check
        against), else a human-readable description of the damage."""
        manifest_path = self._manifest_path(step)
        try:
            manifest = json.loads(manifest_path.read_text())
        except FileNotFoundError:
            return None  # legacy step: no manifest to verify against
        except (OSError, ValueError) as e:
            return f"manifest unreadable ({type(e).__name__}: {e})"
        step_dir = self._step_dir(step)
        for rel, meta in manifest.get("files", {}).items():
            p = step_dir / rel
            try:
                size = p.stat().st_size
                if size != meta["size"]:
                    return f"{rel}: size {size} != manifest {meta['size']}"
                if _file_sha256(p) != meta["sha256"]:
                    return f"{rel}: checksum mismatch"
            except OSError as e:
                return f"{rel}: unreadable ({type(e).__name__}: {e})"
        return None

    def _step_looks_damaged(self, step: int) -> str | None:
        """Cheap structural triage for manifest-less steps: orbax's own
        metadata files must exist and parse. Returns a description of
        the damage, or None when the structure is intact (in which case
        a restore failure is more plausibly a template/code bug)."""
        step_dir = self._step_dir(step)
        if not (step_dir / "_CHECKPOINT_METADATA").is_file():
            return "missing _CHECKPOINT_METADATA"
        for p in step_dir.rglob("_METADATA"):
            try:
                json.loads(p.read_text())
            except (OSError, ValueError) as e:
                return (f"{p.relative_to(step_dir).as_posix()} unparsable "
                        f"({type(e).__name__})")
        return None

    def quarantine(self, step: int, reason: str) -> Path:
        """Move a damaged step out of orbax's sight (rename to
        ``corrupt_<step>.quarantined`` — preserved for forensics; the
        ``.quarantined`` suffix keeps orbax's step scanner from parsing
        it as a step) and drop its manifest."""
        step = int(step)
        flight.record("quarantine", step=step, reason=reason)
        step_dir = self._step_dir(step)
        target = self.directory / f"corrupt_{step}.quarantined"
        if target.exists():  # re-quarantine of the same step number
            suffix = 1
            while (self.directory / f"corrupt_{step}.{suffix}.quarantined").exists():
                suffix += 1
            target = self.directory / f"corrupt_{step}.{suffix}.quarantined"
        os.replace(step_dir, target)
        try:
            self._manifest_path(step).unlink()
        except OSError:
            pass  # no manifest (legacy step) — nothing else to drop
        _m_quarantined.inc()
        log.error("checkpoint step %d is corrupt (%s): quarantined to %s",
                  step, reason, target)
        self._mgr.reload()  # orbax must forget the renamed step
        return target

    def save_data_state(self, step: int, state: dict) -> None:
        """Sidecar snapshot of input-pipeline state for ``step`` (see
        :func:`save_data_state`). Sidecars whose checkpoint step orbax
        has pruned (``max_to_keep``) are unlinked here — they no longer
        correspond to any restorable step and would otherwise
        accumulate one file per save forever."""
        save_data_state(self.directory, step, state)
        keep = set(self.all_steps())
        keep.add(int(step))  # an async save may not be finalized yet
        for p in self.directory.glob("data_state_*.json"):
            try:
                s = int(p.stem.rsplit("_", 1)[-1])
            except ValueError:
                continue
            if s not in keep:
                try:
                    p.unlink()
                except OSError as e:
                    # A permission error mid-GC must not fail the SAVE
                    # that triggered it — the sidecar is merely stale.
                    if not isinstance(e, FileNotFoundError):
                        log.warning("sidecar GC could not remove %s: %s", p, e)

    def load_data_state(self, step: int) -> dict | None:
        return load_data_state(self.directory, step)

    def restore(self, state_template: Any, step: int | None = None) -> Any:
        """Restore into the template's shapes/dtypes/shardings.

        ``state_template`` may be a concrete pytree (its arrays are used
        as placement spec) or the result of :func:`abstract_state`.

        ``step=None`` restores the newest **valid** step: candidates
        failing manifest verification — and manifest-less legacy steps
        whose actual restore raises — are quarantined
        (:meth:`quarantine`) and the next-newest step is tried, so one
        truncated write cannot brick the resume path. An explicit
        ``step`` is restored as asked: verification failure raises
        :class:`CheckpointCorruptError` and nothing is renamed.
        """
        template = abstract_state(state_template)
        if step is not None:
            reason = self.verify_step(int(step))
            if reason is not None:
                raise CheckpointCorruptError(
                    f"checkpoint step {step} under {self.directory} failed "
                    f"verification: {reason}")
            return self._mgr.restore(int(step), args=ocp.args.StandardRestore(template))
        # The fault point counts passages of AUTO restores only: an
        # explicit-step restore has no "latest" to damage and must not
        # silently consume a chaos plan's scheduled corruption.
        corrupt_latest = faultinject.fire("checkpoint.restore")
        while True:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoint under {self.directory}")
            if corrupt_latest:  # armed fault: at-rest damage, found now
                corrupt_latest = False
                faultinject.corrupt_directory(self._step_dir(step))
            reason = self.verify_step(step)
            if reason is None:
                try:
                    return self._mgr.restore(
                        step, args=ocp.args.StandardRestore(template))
                except Exception as e:  # noqa: BLE001 — filtered just below
                    if self._manifest_path(step).exists():
                        # Checksums passed, restore still failed: the
                        # files are intact, so this is a template/code
                        # error, not corruption — quarantining would
                        # destroy a good checkpoint.
                        raise
                    damage = self._step_looks_damaged(step)
                    if damage is None:
                        # Manifest-less (legacy) step whose structure
                        # is intact: a caller-side template bug raises
                        # here too, and quarantining on it would eat
                        # EVERY pre-manifest checkpoint one loop
                        # iteration at a time. Only demonstrable
                        # damage gets a legacy step quarantined.
                        raise
                    reason = f"restore failed ({type(e).__name__}: {e}); {damage}"
            self.quarantine(step, reason)

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        return list(self._mgr.all_steps())

    def wait(self) -> None:
        self._mgr.wait_until_finished()
        self._flush_manifests()

    def close(self) -> None:
        self._mgr.close()  # waits for in-flight saves first
        self._flush_manifests()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def restore_or_init(state: Any, directory: str | Path | None = None) -> tuple[Any, int]:
    """Auto-resume: latest checkpoint if one exists, else ``state`` as-is.

    Returns ``(state, next_step)`` — the step to continue from (0 for a
    fresh run). The wrapper-function pattern stays a straight line:

        state = create_train_state(...)
        state, start = checkpoint.restore_or_init(state)
        for step in range(start, num_steps): ...
    """
    with CheckpointManager(directory, async_save=False) as mgr:
        if mgr.latest_step() is None:
            return state, 0
        # Auto-restore: a corrupt/partial newest step is quarantined and
        # the newest VALID one restores instead (see CheckpointManager
        # .restore) — after which latest_step() IS the restored step.
        try:
            restored = mgr.restore(state)
        except FileNotFoundError:
            # Every candidate step was quarantined: a fresh start is
            # the correct (and loudly logged) outcome.
            log.error("all checkpoint steps under %s were corrupt; "
                      "starting from step 0", mgr.directory)
            return state, 0
        step = mgr.latest_step()
        log.info("resumed from checkpoint step=%d dir=%s", step, mgr.directory)
        return restored, step + 1
