"""End-to-end QoS classes and brownout degradation.

Gray-failure tolerance needs the whole stack to agree on two small
pieces of shared state, and this module is where they live so the
fleet router (top), the serving host (middle), and the feature /
LM layers (bottom) can all import it without cycles:

- **Priority classes.** Two classes, ``interactive`` and ``batch``
  (``X-Priority`` header, or per-tenant config). Interactive is the
  latency SLO; batch is throughput that must yield first under
  pressure. The class rides a contextvar from the HTTP handler down
  through the batcher/joins/LM admission of the SAME request, and the
  router relays the header on every forward so subprocess replicas see
  it too. Untrusted headers can only *lower* a tenant's configured
  class, never raise it.
- **Brownout state.** Under sustained SLO burn the router's
  :class:`BrownoutController` walks a level ladder — 0 (normal),
  1 (*degrade*: feature joins stop waiting on slow shards and serve
  defaults, LM decode budgets shrink), 2 (*shed*: batch-class traffic
  is refused at the front door) — with hysteresis on both edges so one
  bursty tick doesn't flap the fleet. The level is published here
  (:func:`set_brownout` / :func:`brownout_level`) with a hold TTL:
  in-process components read it directly, and subprocess replicas
  adopt it per-request from the ``X-Hops-Brownout`` header the router
  stamps on forwards while browned out. Interactive traffic is shed
  only by the mechanisms that already existed (rate limits,
  ``max_inflight``) — brownout's whole point is to spend quality and
  batch capacity BEFORE touching the interactive class.
- **Bounded priority queues.** :class:`BoundedPriorityQueue` is the
  one sanctioned priority-queue shape for the serving tiers (the
  ``unbounded-priority-queue`` lint rule enforces that queues there
  declare a bound): a hard bound with a shed-lowest-class-first
  eviction policy, FIFO within a class, and a starvation guard — after
  ``starvation_limit`` consecutive higher-class pops while lower-class
  work waits, the oldest lower-class item is served regardless, so
  batch makes progress under any sustained interactive load.

See docs/operations.md "Tail latency & QoS".
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import dataclasses
import threading
import time
from typing import Any, Callable, Iterator, Sequence

PRIORITIES = ("interactive", "batch")
PRIORITY_HEADER = "X-Priority"
BROWNOUT_HEADER = "X-Hops-Brownout"

#: Brownout levels (the ladder the controller walks).
NORMAL, DEGRADE, SHED = 0, 1, 2


def rank(priority: str) -> int:
    """Smaller = more important. Unknown classes collapse to batch —
    an unrecognized claim must not jump the queue."""
    try:
        return PRIORITIES.index(priority)
    except ValueError:
        return len(PRIORITIES) - 1


def parse_priority(header_value: str | None,
                   configured: str | None = None) -> str:
    """Resolve a request's class from its ``X-Priority`` header and the
    tenant's configured class. The header is untrusted client input: it
    can DEMOTE relative to the tenant's configured class (a batch tool
    on an interactive tenant may self-identify), never promote past it.
    No signal at all means interactive — humans are the default."""
    base = configured if configured in PRIORITIES else None
    claimed = (header_value or "").strip().lower()
    claimed = claimed if claimed in PRIORITIES else None
    if base is None and claimed is None:
        return PRIORITIES[0]
    if base is None:
        return claimed
    if claimed is None:
        return base
    return claimed if rank(claimed) >= rank(base) else base


# -- the request's class, riding the call stack --------------------------------

_current_priority: contextvars.ContextVar[str] = contextvars.ContextVar(
    "hops_tpu_qos_priority", default=PRIORITIES[0])


def request_priority() -> str:
    """The priority class of the request this thread is serving."""
    return _current_priority.get()


@contextlib.contextmanager
def priority_scope(priority: str) -> Iterator[None]:
    token = _current_priority.set(
        priority if priority in PRIORITIES else PRIORITIES[0])
    try:
        yield
    finally:
        _current_priority.reset(token)


# -- the brownout level, scoped per endpoint ------------------------------------
#
# Originally ONE process-wide level — which meant a multi-fleet host
# (two in-process fleets, or a serving replica co-located with an
# online-serving daemon) browned out EVERY endpoint the moment one
# model's SLO burned. Levels are now keyed by a *scope* string (a fleet
# or model name; ``""`` is the legacy process-global scope, kept for
# standalone daemons and existing callers). The effective level a
# component sees is ``max(global, its scope)`` — the global scope can
# still degrade the whole host (an operator big-red-switch), but one
# endpoint's controller only touches its own scope.
#
# The scope rides the request context like the priority class does
# (:func:`brownout_scope`): the HTTP handler enters its endpoint's
# scope, and every layer underneath (feature joins, LM decode budgets)
# reads :func:`brownout_level` with no arguments and resolves the
# request's own endpoint.

_brownout_lock = threading.Lock()
#: scope -> (level, expires_monotonic). guarded by: _brownout_lock
_brownout_state: dict[str, tuple[int, float]] = {}

_current_brownout_scope: contextvars.ContextVar[str] = contextvars.ContextVar(
    "hops_tpu_qos_brownout_scope", default="")


@contextlib.contextmanager
def brownout_scope(scope: str) -> Iterator[None]:
    """Bind the brownout scope of the request this context serves (the
    endpoint's fleet/model name). Rides ``contextvars`` into batcher
    and join layers exactly like :func:`priority_scope`."""
    token = _current_brownout_scope.set(scope or "")
    try:
        yield
    finally:
        _current_brownout_scope.reset(token)


def set_brownout(level: int, hold_s: float = 3.0,
                 clock: Callable[[], float] = time.monotonic,
                 scope: str = "") -> None:
    """Publish the brownout level for ``scope`` with a hold TTL. The
    TTL is the fail-safe direction: if the controller (or the router
    stamping headers at a subprocess replica) dies, the fleet drifts
    back to full quality instead of staying degraded forever."""
    with _brownout_lock:
        lvl = max(0, int(level))
        if lvl == 0:
            _brownout_state.pop(scope or "", None)
        else:
            _brownout_state[scope or ""] = (lvl, clock() + hold_s)


def _level_locked(scope: str, now: float) -> int:  # guarded by: _brownout_lock
    state = _brownout_state.get(scope)
    if state is None:
        return 0
    level, expires = state
    return 0 if now >= expires else level


def brownout_level(clock: Callable[[], float] = time.monotonic,
                   scope: str | None = None) -> int:
    """The effective level for ``scope`` (default: the scope bound to
    the current request context, or the global scope outside one) —
    the max of the global level and the scoped level, each under its
    own TTL."""
    if scope is None:
        scope = _current_brownout_scope.get()
    now = clock()
    with _brownout_lock:
        level = _level_locked("", now)
        if scope:
            level = max(level, _level_locked(scope, now))
        return level


def note_remote_brownout(header_value: str | None,
                         hold_s: float = 3.0, scope: str = "") -> None:
    """Adopt a brownout level relayed on a forward's ``X-Hops-Brownout``
    header (subprocess replicas have no view of the router's
    controller), under the replica's own endpoint scope. Only raises or
    refreshes — expiry is by TTL, so a brief gap in browned-out traffic
    cannot flap the level."""
    if not header_value:
        return
    try:
        level = int(str(header_value).strip())
    except ValueError:
        return
    if level > 0 and level >= brownout_level(scope=scope):
        set_brownout(level, hold_s=hold_s, scope=scope)


@dataclasses.dataclass(frozen=True)
class BrownoutPolicy:
    """When sustained SLO burn degrades the fleet (docs/operations.md
    "Tail latency & QoS")."""

    #: The interactive-class p99 target the controller defends.
    slo_p99_ms: float
    #: p99 above slo for ``burn_window_s`` continuously -> DEGRADE.
    burn_window_s: float = 1.0
    #: p99 above ``shed_factor * slo`` for ``burn_window_s`` -> SHED.
    shed_factor: float = 2.0
    #: p99 below ``exit_factor * slo`` for ``recover_window_s`` steps
    #: the level DOWN one notch (hysteresis: exit_factor < 1).
    exit_factor: float = 0.8
    recover_window_s: float = 2.0

    def __post_init__(self) -> None:
        if self.slo_p99_ms <= 0:
            raise ValueError("slo_p99_ms must be > 0")
        if not 0 < self.exit_factor < 1:
            raise ValueError("exit_factor must be in (0, 1) (hysteresis)")
        if self.shed_factor < 1:
            raise ValueError("shed_factor must be >= 1")


class BrownoutController:
    """Walks the brownout ladder from an observed p99 stream.

    ``observe(p99_ms)`` is called on the owner's cadence (the router's
    scrape loop); it returns the current level. Deterministic under an
    injected clock. The controller only COMPUTES the level — publishing
    it (:func:`set_brownout`, metrics, flight events) stays with the
    owner, which knows the model name and hold semantics.
    """

    def __init__(self, policy: BrownoutPolicy,
                 clock: Callable[[], float] = time.monotonic):
        self.policy = policy
        self._clock = clock
        self.level = 0
        self._burn_since: float | None = None
        self._shed_burn_since: float | None = None
        self._clear_since: float | None = None

    def observe(self, p99_ms: float | None) -> int:
        now = self._clock()
        p = self.policy
        if p99_ms is None:
            # No signal: hold the level, reset edge timers (we can't
            # claim the burn is sustained through a blind spot).
            self._burn_since = self._shed_burn_since = self._clear_since = None
            return self.level
        burning = p99_ms > p.slo_p99_ms
        shed_burning = p99_ms > p.slo_p99_ms * p.shed_factor
        clearing = p99_ms < p.slo_p99_ms * p.exit_factor

        def edge(since: float | None, active: bool) -> float | None:
            # Explicit None checks: a timestamp of 0.0 (injected test
            # clocks start there) is a REAL edge, not "unset".
            if not active:
                return None
            return now if since is None else since

        self._burn_since = edge(self._burn_since, burning)
        self._shed_burn_since = edge(self._shed_burn_since, shed_burning)
        self._clear_since = edge(self._clear_since, clearing)
        if (self._shed_burn_since is not None
                and now - self._shed_burn_since >= p.burn_window_s):
            self.level = SHED
        elif (self._burn_since is not None
                and now - self._burn_since >= p.burn_window_s):
            self.level = max(self.level, DEGRADE)
        elif (self.level > 0 and self._clear_since is not None
                and now - self._clear_since >= p.recover_window_s):
            self.level -= 1
            self._clear_since = now  # the next notch needs its own window
        return self.level


# -- bounded priority queue ----------------------------------------------------


class ShedError(RuntimeError):
    """Raised to the producer whose item was refused or evicted by a
    :class:`BoundedPriorityQueue` shed (serving maps it to a 503)."""


class QueueFullError(ShedError):
    """Typed admission reject: a bounded submit queue is at capacity,
    so the request is refused at the door instead of buffering
    unboundedly. A load signal, not a failure — serving maps it to
    503 ``reason="overload"`` with Retry-After, no breaker strike."""


class StarvationGuard:
    """After ``limit`` consecutive higher-class picks while lower-class
    work waits, the next pick MUST take the most-starved class. One
    instance per queue/admission site; not thread-safe by itself (call
    under the owner's lock)."""

    def __init__(self, limit: int = 8):
        if limit < 1:
            raise ValueError("starvation limit must be >= 1")
        self.limit = limit
        self._preferred_streak = 0

    def pick_rank(self, ranks_waiting: Sequence[int]) -> int:
        """Which rank to serve, given the (non-empty) set of ranks with
        queued work."""
        best, worst = min(ranks_waiting), max(ranks_waiting)
        if worst > best and self._preferred_streak >= self.limit:
            self._preferred_streak = 0
            return worst
        if worst > best:
            self._preferred_streak += 1
        else:
            self._preferred_streak = 0
        return best


class BoundedPriorityQueue:
    """A hard-bounded priority queue that sheds lowest class first.

    ``put(item, rank)`` admits unless the queue is full; full, it
    evicts the NEWEST item of the worst (highest-rank) class that is
    strictly worse than the incoming item — shedding the least
    important, least-sunk work — and returns it so the caller can fail
    its producer with :class:`ShedError`. If nothing queued is worse,
    the incoming item itself is refused (raises :class:`ShedError`).
    ``get`` serves FIFO within a class, best class first, under a
    :class:`StarvationGuard`. Ranks below 0 are control items
    (sentinels) and are never evicted or counted by the guard.
    """

    def __init__(self, bound: int, *, starvation_limit: int = 8):
        if bound < 1:
            raise ValueError("BoundedPriorityQueue needs a bound >= 1")
        self.bound = bound
        self._cv = threading.Condition()
        self._lanes: dict[int, collections.deque] = {}  # guarded by: self._cv
        # Queued non-control items (sentinels on negative ranks are
        # excluded from the bound).
        self._size = 0  # guarded by: self._cv
        self._guard = StarvationGuard(starvation_limit)  # guarded by: self._cv

    def qsize(self) -> int:
        with self._cv:
            return self._size

    def put(self, item: Any, rank: int = 0) -> Any | None:
        """Admit ``item``; returns an evicted lower-class item (the
        caller owns failing it) or None. Raises :class:`ShedError` when
        the queue is full of equal-or-better work."""
        with self._cv:
            evicted = None
            if rank >= 0 and self._size >= self.bound:
                worst = max(
                    (r for r, lane in self._lanes.items() if r > rank and lane),
                    default=None,
                )
                if worst is None:
                    raise ShedError(
                        f"priority queue full ({self.bound}) of rank<="
                        f"{rank} work")
                evicted = self._lanes[worst].pop()  # newest of the worst
                self._size -= 1
            self._lanes.setdefault(rank, collections.deque()).append(item)
            if rank >= 0:
                self._size += 1
            self._cv.notify()
            return evicted

    def _pop_locked(self) -> Any:  # guarded by: self._cv
        waiting = [r for r, lane in self._lanes.items() if lane]
        control = [r for r in waiting if r < 0]
        if control:
            return self._lanes[min(control)].popleft()
        r = self._guard.pick_rank(waiting)
        self._size -= 1
        return self._lanes[r].popleft()

    def get(self, timeout: float | None = None) -> Any:
        """Best-class item, FIFO within class; raises ``queue.Empty``
        on timeout (the stdlib contract the batcher loop speaks)."""
        import queue as _queue

        with self._cv:
            if not self._cv.wait_for(
                lambda: any(lane for lane in self._lanes.values()),
                timeout=timeout,
            ):
                raise _queue.Empty
            return self._pop_locked()

    def get_nowait(self) -> Any:
        import queue as _queue

        with self._cv:
            if not any(lane for lane in self._lanes.values()):
                raise _queue.Empty
            return self._pop_locked()
