"""Preemption-safe training: cooperative SIGTERM handling + resume.

TPU pods are preemptible infrastructure: maintenance events and
scheduler evictions deliver SIGTERM with a grace window. The reference
has no story here (a killed run restarts from scratch — SURVEY.md §5
"no auto-resume of a killed run"). The TPU-native pattern is
cooperative: a signal cannot safely interrupt a dispatched XLA program,
so the handler only sets a flag and the training loop checks it at
step boundaries — checkpoint, then exit cleanly, and the restarted job
resumes via :func:`hops_tpu.runtime.checkpoint.restore_or_init`.

Multihost: a maintenance event may SIGTERM hosts at slightly different
times, but every process must leave the collective at the SAME step or
the stragglers deadlock in their next all-reduce. ``should_stop
(sync=True)`` agrees globally (any-host max over a tiny device
all-reduce), so the loop exits coherently.

    guard = PreemptionGuard()
    state, start = checkpoint.restore_or_init(state)
    with CheckpointManager() as ckpt:
        for step in range(start, num_steps):
            state, metrics = train_step(state, batch)
            if guard.should_stop(sync=jax.process_count() > 1):
                ckpt.save(step, state, force=True)
                break
"""

from __future__ import annotations

import signal
import threading
import time
from typing import Any

from hops_tpu.runtime import flight
from hops_tpu.runtime.logging import get_logger
from hops_tpu.telemetry.spans import StepTimer

log = get_logger(__name__)


def _batch_examples(batch: Any) -> int | None:
    """Leading-dim row count of a batch pytree (None if shapeless)."""
    try:
        import jax

        leaf = jax.tree.leaves(batch)[0]
        shape = getattr(leaf, "shape", ())
        return int(shape[0]) if len(shape) >= 1 else None
    except Exception:  # noqa: BLE001 — telemetry must not fail the step
        return None


class PreemptionGuard:
    """Flag-based cooperative preemption notice.

    Installs handlers for ``signals`` (default SIGTERM) that set a
    thread-safe flag and chain to any previous handler. The training
    loop polls :meth:`should_stop` at step boundaries; nothing is
    interrupted mid-dispatch. Use as a context manager (or call
    :meth:`uninstall`) to restore the previous handlers.
    """

    def __init__(self, signals: tuple = (signal.Signals.SIGTERM,), install: bool = True):
        self._flag = threading.Event()
        self._signals = tuple(signals)
        self._previous: dict[Any, Any] = {}
        self._sync_polls = 0  # should_stop(sync=True) decimation counter
        if install:
            self.install()

    # -- lifecycle -----------------------------------------------------------

    def install(self) -> "PreemptionGuard":
        if self._previous:
            return self  # already installed: re-chaining would make the
            # handler its own "previous" and recurse on delivery
        for sig in self._signals:
            self._previous[sig] = signal.signal(sig, self._handler)
        return self

    def uninstall(self) -> None:
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous.clear()

    def __enter__(self) -> "PreemptionGuard":
        if not self._previous:
            self.install()
        return self

    def __exit__(self, *exc) -> None:
        self.uninstall()

    def _handler(self, signum, frame) -> None:
        log.warning("preemption notice (signal %s): will stop at the next "
                    "step boundary", signum)
        # Signal-handler context: flight.record is async-signal-unsafe
        # in theory (it takes a lock) but never blocks on anything that
        # could be interrupted mid-hold by THIS handler, and by
        # contract it never raises.
        flight.record("preemption", signal=int(signum))
        self._flag.set()
        prev = self._previous.get(signum)
        if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
            prev(signum, frame)

    # -- polling -------------------------------------------------------------

    def notice(self) -> None:
        """Programmatic preemption (tests, external watchers)."""
        self._flag.set()

    def should_stop(self, sync: bool = False, sync_every: int = 1) -> bool:
        """True once a preemption notice arrived.

        ``sync=True``: agree across ALL processes (any-host max) so a
        multihost loop exits at one coherent step boundary. Costs one
        tiny all-reduce per poll. ``sync_every=k`` decimates that cost:
        only every k-th poll performs the allgather (an internal poll
        counter, shared across hosts because every host polls once per
        step); the polls in between return False even when the LOCAL
        flag is set, so an agreed stop still lands on a common
        k-boundary — a host that answered its own flag early would
        leave the stragglers deadlocked in their next collective.
        """
        import jax

        if sync_every < 1:
            raise ValueError(f"sync_every must be >= 1, got {sync_every}")
        local = self._flag.is_set()
        if not sync or jax.process_count() == 1:
            return local
        poll = self._sync_polls
        self._sync_polls += 1
        if poll % sync_every:
            return False  # off-boundary: defer so every host agrees
        from jax.experimental import multihost_utils
        import numpy as np

        flags = multihost_utils.process_allgather(
            np.asarray([local], dtype=np.int32))
        agreed = bool(flags.max())
        if agreed and not local:
            log.warning("another host was preempted: stopping at this "
                        "step boundary")
            self._flag.set()
        return agreed


def run_preemptible(
    train_step,
    state: Any,
    batches,
    *,
    directory: str | None = None,
    save_every: int = 100,
    sync: bool | None = None,
    sync_every: int = 1,
    guard: PreemptionGuard | None = None,
    max_recoveries: int = 0,
    recovery_policy: Any = None,
):
    """Checkpointed, preemption-safe training loop.

    Resumes from the latest checkpoint under ``directory`` (the active
    run's ``checkpoints/`` by default), steps through ``batches``,
    saves every ``save_every`` steps, and on preemption saves once more
    and returns early. Returns ``(state, last_metrics, completed_steps)``.

    ``batches`` is either a plain iterable — steps already completed
    before resume are drawn and discarded — or a callable
    ``batches(start_step) -> iterable`` that produces the stream
    already fast-forwarded (e.g. a ``featurestore.DataLoader``, or
    ``lambda k: feeder.numpy_iterator(..., start_step=k)``), so resume
    skips no data materialization at all.

    Resumable iterators (anything exposing ``state_dict`` /
    ``load_state_dict`` — the loader pipeline's iterators): each
    checkpoint save also writes a data-state sidecar
    (``checkpoint.save_data_state``), and resume repositions the
    iterator from the restored step's sidecar, so the exact remaining
    batch stream replays deterministically.

    ``sync_every=k`` decimates the multihost stop-agreement allgather
    to every k-th step (see :meth:`PreemptionGuard.should_stop`).

    **Supervisor mode** (``max_recoveries > 0``): a transient step or
    feed failure no longer kills the run. The exception is caught, the
    state is re-restored from the newest *valid* checkpoint (a corrupt
    latest step is quarantined by ``CheckpointManager.restore``), the
    batch stream is rebuilt at the restored position, and the loop
    resumes — up to ``max_recoveries`` times, backing off between
    attempts under ``recovery_policy`` (a ``resilience.RetryPolicy``;
    default: 3 attempts irrelevant here, only its delay schedule is
    used). Each recovery increments ``hops_tpu_run_recoveries_total``.
    Requires ``batches`` to be re-derivable: a callable, a resumable
    iterator, or a re-iterable sequence (a one-shot generator cannot
    be replayed and exhausts recovery). Preemption notices and
    ``KeyboardInterrupt``/``SystemExit`` are never treated as
    recoverable.
    """
    import jax

    from hops_tpu.runtime.resilience import RetryPolicy
    from hops_tpu.telemetry.metrics import REGISTRY

    own_guard = guard is None
    guard = guard or PreemptionGuard()
    # The crash path of the flight recorder: an unhandled failure in
    # this (supervised) loop dumps the event ring to the rundir.
    flight.install_crash_handler()
    if sync is None:
        sync = jax.process_count() > 1
    policy = recovery_policy or RetryPolicy(base_delay_s=0.05, max_delay_s=5.0)
    import random

    backoff_rng = random.Random(policy.seed) if policy.seed is not None else None
    m_recoveries = REGISTRY.counter(
        "hops_tpu_run_recoveries_total",
        "Supervisor recoveries (re-restore + resume after a transient "
        "step/feed failure), per loop",
        labels=("loop",),
    )
    recoveries = 0
    try:
        while True:
            try:
                return _run_attempt(
                    train_step, state, batches, directory=directory,
                    save_every=save_every, sync=sync, sync_every=sync_every,
                    guard=guard)
            except Exception as e:  # noqa: BLE001 — bounded supervisor retry
                if recoveries >= max_recoveries:
                    raise
                recoveries += 1
                m_recoveries.inc(loop="preemptible")
                flight.record("recovery", loop="preemptible",
                              attempt=recoveries,
                              error=f"{type(e).__name__}: {e}")
                pause = policy.delay(recoveries - 1, backoff_rng)
                log.warning(
                    "run_preemptible: transient failure (%s: %s); recovery "
                    "%d/%d — re-restoring from checkpoint in %.2fs",
                    type(e).__name__, e, recoveries, max_recoveries, pause)
                time.sleep(pause)
    finally:
        if own_guard:
            guard.uninstall()


def _run_attempt(
    train_step,
    state: Any,
    batches,
    *,
    directory: str | None,
    save_every: int,
    sync: bool,
    sync_every: int,
    guard: PreemptionGuard,
):
    """One incarnation of the train loop: restore, step, checkpoint.
    Raises on step/feed failure — the supervisor in
    :func:`run_preemptible` decides whether that is fatal."""
    from hops_tpu.runtime.checkpoint import (
        CheckpointManager,
        load_data_state,
        restore_or_init,
    )

    state, start = restore_or_init(state, directory)
    metrics = None
    step = start - 1
    src = batches(start) if callable(batches) else batches
    resumable = hasattr(src, "state_dict") and hasattr(src, "load_state_dict")
    data_state = load_data_state(directory, start - 1) if start else None
    if resumable and data_state is not None:
        # The sidecar's position (next-unyielded batch at save time) is
        # authoritative — it repositions even streams the callable path
        # already fast-forwarded, covering iterators whose position is
        # not a pure function of the step count.
        src.load_state_dict(data_state)
    if callable(batches) or (resumable and data_state is not None):
        stream = enumerate(src, start=start)
    else:
        stream = enumerate(src)
    # Step-cadence telemetry: step time, steps/examples counters, and
    # the heartbeat gauges — the signal a diagnostics.Watchdog(
    # watch_heartbeat_gauge="preemptible") reads instead of needing an
    # explicit heartbeat() call wired into the loop.
    timer = StepTimer(loop="preemptible")
    timer.arm()
    with CheckpointManager(directory, save_interval_steps=save_every) as ckpt:
        saved = ran = False
        for step, batch in stream:
            if step < start:
                continue  # consumed by a previous incarnation
            ran = True
            state, metrics = train_step(state, batch)
            timer.tick(examples=_batch_examples(batch))
            saved = ckpt.save(step, state)  # interval save
            if saved and resumable:
                ckpt.save_data_state(step, src.state_dict())
            if guard.should_stop(sync=sync, sync_every=sync_every):
                if not saved:
                    # orbax refuses to overwrite an existing step
                    # even with force=True — only save if the
                    # interval save didn't just write this step.
                    ckpt.save(step, state, force=True)
                    if resumable:
                        ckpt.save_data_state(step, src.state_dict())
                log.warning("preempted: checkpointed step %d, exiting "
                            "cleanly", step)
                break
        else:
            # Normal completion: make the final state durable too —
            # otherwise up to save_every-1 finished steps would be
            # redone by the next incarnation after a hard kill.
            if ran and not saved:
                ckpt.save(step, state, force=True)
                if resumable:
                    ckpt.save_data_state(step, src.state_dict())
        ckpt.wait()
    return state, metrics, step + 1
