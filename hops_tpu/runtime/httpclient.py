"""Pooled keep-alive HTTP client for the fleet's loopback control plane.

The serving stack's cross-process hops (router→replica forwards,
``/metrics.json`` scrapes, shadow probes, hedges) all used
``urllib.request.urlopen``, which opens and tears down a TCP connection
per call — ~5 ms per hop pair on the CPU tier, the dominant per-hop
cost once the relay went zero-copy (ROADMAP item 4 follow-up). This
module is the fix: a thread-safe pool of persistent
``http.client.HTTPConnection`` objects keyed by ``(host, port)``.

Semantics the callers rely on:

- **An explicit timeout on every request** (keyword-only — the fleet's
  ``blocking-call-no-deadline`` discipline). The timeout is applied to
  the pooled socket per request, so a connection checked out for a
  30 s forward and later reused for a 0.5 s scrape honors each budget.
- **Status codes are data, not exceptions.** 4xx/5xx return like 2xx
  — exactly the router relay's contract (urllib's ``HTTPError``
  special-casing disappears). Only transport failures raise, and they
  raise ``OSError`` subclasses (``http.client`` protocol errors are
  wrapped), so every existing ``except (OSError, ...)`` retry path
  catches pool errors unchanged.
- **Stale keep-alives retry once.** A server may close an idle pooled
  connection at any time; a send/recv failure on a REUSED connection
  retries once on a fresh one before surfacing. A failure on a fresh
  connection is real and raises immediately. Requests through this
  pool must therefore stay idempotent (predict is; scrapes are) —
  the same contract the router's retry-elsewhere policy already set.
- **Hedging rides the same pool**: a hedge checks out its own
  connection, so the second attempt never pays a handshake and never
  shares a socket with the primary.

The server side of the bargain: the router and serving handlers declare
``protocol_version = "HTTP/1.1"`` and always send Content-Length, so
connections actually survive between requests.

Every exchange first passes the ``transport.send`` fault point
(:func:`faultinject.fire_transport`) under the pool's ``identity`` as
the source — the seam where the deterministic network-partition
simulator cuts links (docs/operations.md "Partition tolerance &
fencing"). Disarmed, that is one ``is None`` test.
"""

from __future__ import annotations

import http.client
import socket
import threading
from typing import Any, Mapping
from urllib.parse import urlsplit

from hops_tpu.runtime import faultinject
from hops_tpu.runtime.logging import get_logger

log = get_logger(__name__)


class HTTPPool:
    """Persistent-connection pool; one instance per client (the router
    owns one). ``max_idle_per_host`` bounds parked connections per
    ``(host, port)`` — extras close instead of parking. ``identity``
    names this pool as the SOURCE side of partition keys
    (``src->dst``); give each logical client its own so asymmetric
    cuts can tell the router from a hostd from a bench client."""

    def __init__(self, max_idle_per_host: int = 8, *,
                 identity: str = "client"):
        self.max_idle_per_host = max_idle_per_host
        self.identity = identity
        self._lock = threading.Lock()
        self._idle: dict[tuple[str, int], list[http.client.HTTPConnection]] = {}  # guarded by: self._lock
        self._closed = False  # guarded by: self._lock
        self.reused = 0  # connections served from the pool (telemetry)
        self.created = 0

    # -- connection checkout/checkin ------------------------------------------

    def _checkout(self, host: str, port: int,
                  timeout_s: float) -> tuple[http.client.HTTPConnection, bool]:
        with self._lock:
            stack = self._idle.get((host, port))
            conn = stack.pop() if stack else None
            if conn is not None:
                self.reused += 1
        if conn is not None:
            conn.timeout = timeout_s
            if conn.sock is not None:
                conn.sock.settimeout(timeout_s)
            return conn, True
        with self._lock:
            self.created += 1
        return http.client.HTTPConnection(host, port, timeout=timeout_s), False

    def _checkin(self, host: str, port: int,
                 conn: http.client.HTTPConnection) -> None:
        with self._lock:
            if not self._closed:
                stack = self._idle.setdefault((host, port), [])
                if len(stack) < self.max_idle_per_host:
                    stack.append(conn)
                    return
        conn.close()

    # -- the request ----------------------------------------------------------

    def request(
        self,
        method: str,
        url: str,
        body: bytes | None = None,
        headers: Mapping[str, str] | None = None,
        *,
        timeout_s: float,
    ) -> tuple[int, bytes, dict[str, str]]:
        """One HTTP exchange; returns ``(status, body, headers)`` with
        4xx/5xx as data. Transport failures raise OSError subclasses."""
        parts = urlsplit(url)
        host = parts.hostname or "127.0.0.1"
        port = parts.port or 80
        path = parts.path or "/"
        if parts.query:
            path = f"{path}?{parts.query}"
        faultinject.fire_transport(self.identity, f"{host}:{port}")
        last_exc: Exception | None = None
        for fresh_retry in (False, True):
            conn, reused = self._checkout(host, port, timeout_s)
            try:
                if conn.sock is None:
                    # http.client sends headers and body as separate
                    # writes; with Nagle on, the body write stalls for
                    # the peer's delayed ACK (~40 ms) once the
                    # connection leaves quickack mode — on reused
                    # keep-alives that stall dwarfs the request itself.
                    conn.connect()
                    conn.sock.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                conn.request(method, path, body=body, headers=dict(headers or {}))
                resp = conn.getresponse()
                data = resp.read()
                hdrs = dict(resp.headers.items())
                if resp.will_close:
                    conn.close()
                else:
                    self._checkin(host, port, conn)
                return resp.status, data, hdrs
            except (OSError, http.client.HTTPException) as e:
                conn.close()
                if (reused and not fresh_retry
                        and not isinstance(e, TimeoutError)):
                    # A parked keep-alive the server closed under us:
                    # not a peer failure — retry once on a fresh
                    # connection before letting the caller's retry /
                    # breaker policy see anything. A TIMEOUT is
                    # excluded: that is the peer being slow, and a
                    # retry would double the caller's deadline and
                    # re-send the request to the very peer that is
                    # already drowning.
                    last_exc = e
                    continue
                if isinstance(e, http.client.HTTPException):
                    raise ConnectionError(
                        f"http protocol failure talking to "
                        f"{host}:{port}: {type(e).__name__}: {e}"
                    ) from e
                raise
        raise ConnectionError(  # pragma: no cover — loop always returns/raises
            f"connection to {host}:{port} failed: {last_exc}"
        ) from last_exc

    # -- multiplexing: pipelined batches --------------------------------------

    @staticmethod
    def _split(url: str) -> tuple[str, int, str]:
        parts = urlsplit(url)
        path = parts.path or "/"
        if parts.query:
            path = f"{path}?{parts.query}"
        return parts.hostname or "127.0.0.1", parts.port or 80, path

    def pipeline(
        self,
        requests: "list[tuple[str, str, bytes | None, Mapping[str, str] | None]]",
        *,
        timeout_s: float,
    ) -> list[tuple[int, bytes, dict[str, str]]]:
        """In-flight HTTP/1.1 pipelining on ONE pooled connection: every
        request in the batch — ``(method, url, body, headers)`` tuples,
        all on the same ``(host, port)`` — is written back-to-back
        before the first response is read, then responses are read in
        request order. One syscall burst and one connection for a whole
        scrape/probe batch instead of a request-response round trip
        each (the event-loop server core parses and answers pipelined
        requests in order; see ``runtime/httpserver``).

        All-or-nothing: any transport failure raises for the whole
        batch (an ``OSError`` subclass, like :meth:`request`) — callers
        that need per-request isolation use :meth:`get_many`, which
        falls back to sequential requests. Batches must therefore stay
        idempotent, the same contract as the stale-keep-alive retry."""
        if not requests:
            return []
        host, port, _ = self._split(requests[0][1])
        wire = bytearray()
        methods: list[str] = []
        for method, url, body, headers in requests:
            h, p, path = self._split(url)
            if (h, p) != (host, port):
                raise ValueError(
                    f"pipeline batch spans hosts: {host}:{port} vs {h}:{p}")
            methods.append(method)
            lines = [f"{method} {path} HTTP/1.1\r\n", f"Host: {host}:{port}\r\n"]
            for k, v in dict(headers or {}).items():
                lines.append(f"{k}: {v}\r\n")
            if body is not None or method in ("POST", "PUT", "PATCH"):
                lines.append(f"Content-Length: {len(body or b'')}\r\n")
            lines.append("\r\n")
            wire += "".join(lines).encode("latin-1")
            if body:
                wire += body
        faultinject.fire_transport(self.identity, f"{host}:{port}")
        conn, reused = self._checkout(host, port, timeout_s)
        try:
            if conn.sock is None:
                conn.connect()
                conn.sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.sock.settimeout(timeout_s)
            conn.sock.sendall(wire)
            out: list[tuple[int, bytes, dict[str, str]]] = []
            will_close = False
            # One shared buffered reader for the whole batch:
            # a fresh HTTPResponse per response would each wrap the
            # socket in its OWN buffer and swallow the next pipelined
            # response's bytes.
            fp = conn.sock.makefile("rb")
            try:
                for _ in methods:
                    status_line = fp.readline(65536)
                    parts = status_line.split(None, 2)
                    if len(parts) < 2 or not parts[0].startswith(b"HTTP/"):
                        raise http.client.BadStatusLine(
                            status_line.decode("latin-1", "replace"))
                    status = int(parts[1])
                    msg = http.client.parse_headers(fp)
                    if "chunked" in (
                            msg.get("Transfer-Encoding") or "").lower():
                        raise http.client.HTTPException(
                            "chunked responses are not pipelinable here")
                    length = int(msg.get("Content-Length") or 0)
                    data = fp.read(length) if length else b""
                    if length and len(data) < length:
                        raise http.client.IncompleteRead(data, length)
                    out.append((status, data, dict(msg.items())))
                    will_close = will_close or (
                        (msg.get("Connection") or "").lower() == "close"
                        or parts[0] == b"HTTP/1.0")
            finally:
                fp.close()  # drops the buffer; conn still owns the socket
            if will_close:
                conn.close()
            else:
                self._checkin(host, port, conn)
            return out
        except (OSError, http.client.HTTPException) as e:
            conn.close()
            if isinstance(e, http.client.HTTPException):
                raise ConnectionError(
                    f"http protocol failure pipelining to "
                    f"{host}:{port}: {type(e).__name__}: {e}"
                ) from e
            raise

    def get_many(
        self,
        requests: "list[tuple[str, str, bytes | None, Mapping[str, str] | None]]",
        *,
        timeout_s: float,
    ) -> "list[tuple[int, bytes, dict[str, str]] | Exception]":
        """Coalesced batch fetch: requests to the same ``(host, port)``
        are pipelined on one pooled connection; distinct hosts run
        concurrently (one thread per host group). Returns a list
        aligned with ``requests`` where each entry is ``(status, body,
        headers)`` or the ``Exception`` that request raised — one bad
        peer never fails its batch-mates. A pipelined group that fails
        at the transport falls back to per-request :meth:`request`
        (idempotency required, as everywhere in this pool)."""
        results: list[Any] = [None] * len(requests)
        groups: dict[tuple[str, int], list[int]] = {}
        for i, (_, url, _, _) in enumerate(requests):
            host, port, _ = self._split(url)
            groups.setdefault((host, port), []).append(i)

        def run_group(idxs: list[int]) -> None:
            if len(idxs) > 1:
                try:
                    outs = self.pipeline(
                        [requests[i] for i in idxs], timeout_s=timeout_s)
                except OSError:
                    pass  # degrade to per-request isolation below
                else:
                    for i, out in zip(idxs, outs):
                        results[i] = out
                    return
            for i in idxs:
                method, url, body, headers = requests[i]
                try:
                    results[i] = self.request(
                        method, url, body=body, headers=headers,
                        timeout_s=timeout_s)
                except OSError as e:
                    results[i] = e

        grouped = list(groups.values())
        if len(grouped) <= 1:
            for idxs in grouped:
                run_group(idxs)
            return results
        threads = [
            threading.Thread(target=run_group, args=(idxs,), daemon=True)
            for idxs in grouped
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return results

    def idle_count(self) -> int:
        with self._lock:
            return sum(len(s) for s in self._idle.values())

    def close(self) -> None:
        with self._lock:
            self._closed = True
            conns = [c for stack in self._idle.values() for c in stack]
            self._idle.clear()
        for c in conns:
            c.close()
