"""Structured logging for the framework.

The reference scattered logs across Spark executor stdout, per-run
``output.log`` files, and log4j (SURVEY.md §5 "Metrics / logging").
Here: one stdlib-logging-based layer that (a) prefixes records with the
process/host index — the moral equivalent of the per-executor prefixes
Spark gave the reference — and (b) can tee into a per-run ``output.log``
inside the active run directory.
"""

from __future__ import annotations

import json
import logging
import sys
import time
from pathlib import Path
from typing import Any

_FORMAT = "%(asctime)s [%(hosttag)s] %(levelname)s %(name)s: %(message)s"


class _HostTagFilter(logging.Filter):
    def filter(self, record: logging.LogRecord) -> bool:
        if not hasattr(record, "hosttag"):
            # Tag with the host index ONLY if the jax backend is already
            # up. ``process_index()`` would otherwise initialize it as a
            # side effect of logging — which blocks for minutes in
            # processes that can't reach the accelerator (serving hosts,
            # job children competing for a single-tenant TPU relay).
            try:
                from jax._src import xla_bridge

                if xla_bridge.backends_are_initialized():
                    import jax

                    record.hosttag = f"h{jax.process_index()}"
                else:
                    record.hosttag = "h?"
            except Exception:
                record.hosttag = "h?"
        return True


_configured = False


def get_logger(name: str = "hops_tpu") -> logging.Logger:
    # Route every logger under the configured "hops_tpu" hierarchy so
    # user-code loggers inherit the handler, level and host tag.
    if name != "hops_tpu" and not name.startswith("hops_tpu."):
        name = f"hops_tpu.{name}"
    global _configured
    if not _configured:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT))
        handler.addFilter(_HostTagFilter())
        root = logging.getLogger("hops_tpu")
        root.addHandler(handler)
        from hops_tpu.runtime import config

        root.setLevel(config.runtime().log_level)
        root.propagate = False
        _configured = True
    return logging.getLogger(name)


def attach_run_log(path: str | Path) -> logging.Handler:
    """Tee framework logs into a per-run ``output.log`` (the reference
    returned such a path from every launcher — SURVEY.md §2.3)."""
    handler = logging.FileHandler(path)
    handler.setFormatter(logging.Formatter(_FORMAT))
    handler.addFilter(_HostTagFilter())
    logging.getLogger("hops_tpu").addHandler(handler)
    return handler


def detach_run_log(handler: logging.Handler) -> None:
    logging.getLogger("hops_tpu").removeHandler(handler)
    handler.close()


class MetricLogger:
    """Append-only JSONL metric stream for a run (TensorBoard-lite).

    Events: ``{"step": int, "tag": str, "value": float, "time": float}``.
    The experiments UI / tooling reads these; ``hops_tpu.experiment.
    tensorboard`` wraps it behind a SummaryWriter-style API.
    """

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._f = self.path.open("a")

    def log(self, step: int, tag: str, value: Any) -> None:
        self._f.write(
            json.dumps(
                {"step": int(step), "tag": tag, "value": _jsonable(value), "time": time.time()}
            )
            + "\n"
        )
        self._f.flush()

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.close()


def scalarize(v: Any) -> Any:
    """Best-effort float coercion for metric values (str fallback)."""
    try:
        return float(v)
    except (TypeError, ValueError):
        return str(v)


_jsonable = scalarize


def read_metrics(path: str | Path) -> list[dict[str, Any]]:
    """Events from a run's ``metrics.jsonl``. Tolerates a torn tail
    line: the stream is append-only and may be read while the run is
    still writing (live dashboards, hops_tpu.plotting.collect)."""
    p = Path(path)
    if not p.exists():
        return []
    out = []
    for line in p.read_text().splitlines():
        if not line.strip():
            continue
        try:
            out.append(json.loads(line))
        except ValueError:
            continue
    return out
