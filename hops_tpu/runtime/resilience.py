"""Self-healing primitives: retry policies, deadlines, circuit breakers.

The platform's failure story so far was *avoidance* — relay locks that
never SIGKILL, preemption guards that exit cleanly. This module is the
*recovery* half the TF paper treats as table stakes for a platform
(user-level checkpointing + automatic re-execution on transient
failure) and the preemptible-pod reality of TPU slices assumes: I/O and
RPC errors are normal weather, and every layer that talks to storage,
the network, or a flaky device gets one shared vocabulary for retrying:

- :class:`RetryPolicy` — bounded attempts under exponential backoff
  with **full jitter** (the AWS-architecture result: decorrelated
  sleeps beat synchronized retry storms), an optional per-attempt
  deadline and an overall deadline;
- :func:`with_deadline` — run a callable with a hard time budget
  (the serving layer's per-request deadline);
- :class:`CircuitBreaker` — closed/open/half-open protection for a
  dependency that is *down* rather than *flaky*: after
  ``failure_threshold`` consecutive failures the circuit opens and
  callers fail fast (no queue of doomed work), then a single half-open
  probe after ``reset_timeout_s`` decides whether to close again.

Everything here is stdlib-only and emits ``hops_tpu_resilience_*``
telemetry (see docs/operations.md "Failure handling & fault
injection"), so a dashboard can distinguish "retried and healed" from
"gave up" without log spelunking. The one sanctioned home for backoff
loops — the ``naked-retry-loop`` lint rule points here.
"""

from __future__ import annotations

import contextvars
import dataclasses
import random
import threading
import time
from typing import Any, Callable

from hops_tpu.runtime import flight
from hops_tpu.runtime.logging import get_logger
from hops_tpu.telemetry import tracing
from hops_tpu.telemetry.metrics import REGISTRY

log = get_logger(__name__)

_m_retries = REGISTRY.counter(
    "hops_tpu_resilience_retries_total",
    "Retried attempts, per protected operation",
    labels=("op",),
)
_m_giveups = REGISTRY.counter(
    "hops_tpu_resilience_giveups_total",
    "Operations that exhausted their retry budget, per operation",
    labels=("op",),
)
_m_breaker_state = REGISTRY.gauge(
    "hops_tpu_resilience_breaker_state",
    "Circuit-breaker state per breaker: 0 closed, 1 half-open, 2 open",
    labels=("breaker",),
)
_m_breaker_transitions = REGISTRY.counter(
    "hops_tpu_resilience_breaker_transitions_total",
    "Circuit-breaker state transitions, per breaker and target state",
    labels=("breaker", "to"),
)
_m_deadlines = REGISTRY.counter(
    "hops_tpu_resilience_deadline_exceeded_total",
    "Calls abandoned because their deadline elapsed, per operation",
    labels=("op",),
)


class DeadlineExceeded(TimeoutError):
    """A call exceeded its per-attempt or overall deadline."""


class CircuitOpenError(RuntimeError):
    """The circuit is open: the protected dependency is failing fast.

    ``retry_after_s`` is how long until the breaker will admit a
    half-open probe — servers surface it as a ``Retry-After`` header.
    """

    def __init__(self, name: str, retry_after_s: float):
        super().__init__(
            f"circuit {name!r} is open; retry after {retry_after_s:.1f}s"
        )
        self.retry_after_s = retry_after_s


def with_deadline(
    fn: Callable[..., Any],
    timeout_s: float,
    *args: Any,
    op: str = "call",
    **kwargs: Any,
) -> Any:
    """Run ``fn`` with a hard time budget; :class:`DeadlineExceeded` on
    overrun.

    The call runs on a one-shot worker thread so the *caller* honors
    the deadline even when ``fn`` blocks in C code. An overrun
    abandons the worker (daemon thread; it finishes in the background
    and its result is dropped) — use only around calls that are safe
    to abandon, e.g. a predict whose output nobody will read.
    """
    if timeout_s is None or timeout_s <= 0:
        return fn(*args, **kwargs)
    result: list[Any] = []
    error: list[BaseException] = []
    done = threading.Event()
    # Threads do NOT inherit contextvars: copy the caller's context so
    # the worker keeps the active trace span (a deadline-bounded
    # predict must still attribute its time to the request's trace).
    caller_ctx = contextvars.copy_context()

    def _run() -> None:
        try:
            result.append(caller_ctx.run(fn, *args, **kwargs))
        except BaseException as e:  # noqa: BLE001 — transported to the caller
            error.append(e)
        finally:
            done.set()

    t = threading.Thread(target=_run, daemon=True, name=f"deadline-{op}")
    t.start()
    if not done.wait(timeout_s):
        _m_deadlines.inc(op=op)
        flight.record("deadline_exceeded", op=op, timeout_s=timeout_s)
        tracing.add_event("deadline_exceeded", op=op, timeout_s=timeout_s)
        raise DeadlineExceeded(f"{op} exceeded its {timeout_s:.3f}s deadline")
    if error:
        raise error[0]
    return result[0]


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded retries under exponential backoff with full jitter.

    ``max_attempts`` counts the first try; ``delay(k)`` for retry ``k``
    (0-based) draws uniformly from ``[0, min(max_delay_s, base_delay_s
    * multiplier**k)]`` — full jitter, so a fleet of failed workers
    does not re-dogpile the dependency in lockstep. ``attempt_timeout_s``
    bounds each try via :func:`with_deadline`; ``total_timeout_s``
    bounds the whole call including sleeps (no retry starts past it).
    ``retry_on`` names the exception types worth retrying;
    ``no_retry_on`` carves out subtypes that must propagate immediately
    (cooperative-stop signals, assertion bugs).
    """

    max_attempts: int = 3
    base_delay_s: float = 0.1
    max_delay_s: float = 30.0
    multiplier: float = 2.0
    jitter: bool = True
    attempt_timeout_s: float | None = None
    total_timeout_s: float | None = None
    retry_on: tuple[type[BaseException], ...] = (Exception,)
    no_retry_on: tuple[type[BaseException], ...] = ()
    seed: int | None = None  # deterministic jitter for tests

    def delay(self, retry_index: int, rng: random.Random | None = None) -> float:
        cap = min(self.max_delay_s,
                  self.base_delay_s * self.multiplier ** retry_index)
        if not self.jitter:
            return cap
        draw = (rng or random).uniform(0.0, cap)
        return draw

    def retryable(self, exc: BaseException) -> bool:
        if isinstance(exc, self.no_retry_on):
            return False
        return isinstance(exc, self.retry_on)

    def call(self, fn: Callable[..., Any], *args: Any,
             op: str = "call", **kwargs: Any) -> Any:
        """Run ``fn`` under this policy; re-raise the last error once
        the budget (attempts or total deadline) is exhausted."""
        rng = random.Random(self.seed) if self.seed is not None else None
        overall = (time.monotonic() + self.total_timeout_s
                   if self.total_timeout_s else None)
        last: BaseException | None = None
        for attempt in range(max(1, self.max_attempts)):
            try:
                if self.attempt_timeout_s:
                    return with_deadline(
                        fn, self.attempt_timeout_s, *args, op=op, **kwargs)
                return fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — filtered below
                if not self.retryable(e):
                    # Not this policy's business (early-stop signals,
                    # Ctrl-C, assertion bugs): propagate untouched —
                    # counting it as a giveup would page an operator
                    # for normal control flow.
                    raise
                last = e
                if attempt + 1 >= self.max_attempts:
                    break
                pause = self.delay(attempt, rng)
                if overall is not None and time.monotonic() + pause > overall:
                    break
                _m_retries.inc(op=op)
                flight.record("retry", op=op, attempt=attempt + 1,
                              error=type(e).__name__)
                tracing.add_event("retry", op=op, attempt=attempt + 1,
                                  error=type(e).__name__)
                log.warning("%s attempt %d/%d failed (%s: %s); retrying in "
                            "%.3fs", op, attempt + 1, self.max_attempts,
                            type(e).__name__, e, pause)
                time.sleep(pause)
        _m_giveups.inc(op=op)
        flight.record("giveup", op=op,
                      error=type(last).__name__ if last else None)
        assert last is not None
        raise last


#: Map breaker states onto the exported gauge values.
_STATE_VALUE = {"closed": 0, "half_open": 1, "open": 2}


class CircuitBreaker:
    """Closed/open/half-open failure gate around one dependency.

    * **closed** — normal operation; ``failure_threshold`` *consecutive*
      failures trip it open (a success resets the count).
    * **open** — :meth:`allow` is False and :meth:`guard` raises
      :class:`CircuitOpenError` until ``reset_timeout_s`` has passed:
      callers fail fast instead of queueing doomed work.
    * **half-open** — after the timeout, up to ``half_open_max``
      concurrent probes are admitted; a probe success closes the
      circuit, a probe failure re-opens it (fresh timeout).

    Thread-safe; state changes are logged and exported on the
    ``hops_tpu_resilience_breaker_state`` gauge so dashboards and the
    serving ``/healthz`` route agree on readiness.
    """

    def __init__(
        self,
        name: str = "default",
        failure_threshold: int = 5,
        reset_timeout_s: float = 30.0,
        half_open_max: int = 1,
        clock: Callable[[], float] = time.monotonic,
    ):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.name = name
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.half_open_max = half_open_max
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"  # guarded by: self._lock
        self._failures = 0  # guarded by: self._lock
        self._opened_at = 0.0  # guarded by: self._lock
        self._probes = 0  # guarded by: self._lock
        self._changed_at = clock()  # guarded by: self._lock
        self._m_state = _m_breaker_state.labels(breaker=name)
        self._m_state.set(0)

    # -- state machine (callers hold self._lock) ------------------------------

    def _transition(self, to: str) -> None:  # guarded by: self._lock
        if to == self._state:
            return
        log.warning("circuit %s: %s -> %s", self.name, self._state, to)
        flight.record("breaker_transition", breaker=self.name,
                      frm=self._state, to=to)
        tracing.add_event("breaker_transition", breaker=self.name,
                          frm=self._state, to=to)
        self._state = to
        self._changed_at = self._clock()
        self._m_state.set(_STATE_VALUE[to])
        _m_breaker_transitions.inc(breaker=self.name, to=to)
        if to == "open":
            self._opened_at = self._clock()
            self._probes = 0
        elif to == "closed":
            self._failures = 0
            self._probes = 0

    def _poll(self) -> None:  # guarded by: self._lock
        if (self._state == "open"
                and self._clock() - self._opened_at >= self.reset_timeout_s):
            self._transition("half_open")

    # -- public surface -------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            self._poll()
            return self._state

    def state_age_s(self) -> float:
        """Seconds the breaker has been in its current state — the
        router's ``GET /fleet`` view serves this so a just-opened
        breaker reads differently from one stuck open for an hour."""
        with self._lock:
            self._poll()
            return max(0.0, self._clock() - self._changed_at)

    def retry_after_s(self) -> float:
        """Seconds until the breaker admits a half-open probe (0 when
        it already would)."""
        with self._lock:
            self._poll()
            if self._state != "open":
                return 0.0
            return max(
                0.0, self.reset_timeout_s - (self._clock() - self._opened_at))

    def allow(self) -> bool:
        """May a call proceed right now? Half-open admissions count
        against ``half_open_max`` until their success/failure reports."""
        with self._lock:
            self._poll()
            if self._state == "closed":
                return True
            if self._state == "half_open" and self._probes < self.half_open_max:
                self._probes += 1
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._state == "half_open":
                self._transition("closed")

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._state == "half_open":
                self._transition("open")
            elif (self._state == "closed"
                    and self._failures >= self.failure_threshold):
                self._transition("open")

    def guard(self):
        """Context manager: raises :class:`CircuitOpenError` when the
        call may not proceed, records success/failure from the body."""
        return _BreakerGuard(self)


class _BreakerGuard:
    def __init__(self, breaker: CircuitBreaker):
        self._b = breaker

    def __enter__(self) -> CircuitBreaker:
        if not self._b.allow():
            raise CircuitOpenError(self._b.name, self._b.retry_after_s())
        return self._b

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self._b.record_success()
        else:
            self._b.record_failure()
