"""Packed columnar wire codec for tensor-shaped payloads.

PR 17's event-loop transport removed the per-hop HTTP overhead, which
left Python-side JSON as the dominant per-request cost on the serving
and feature data planes: replicas ``json.loads`` instance bodies, remote
feature shards ship ``multi_get`` rows as JSON, and the online store
decodes row JSON per batch. This module is the TF-Serving-style answer —
a versioned, typed, columnar binary frame that decodes zero-copy via
``np.frombuffer`` and encodes straight from C-contiguous arrays with no
Python-level float loop. Semantics stay exact: the packed and JSON paths
are pinned bit-identical by tests.

Frame layout (all integers little-endian, ``struct`` ``<``)::

    offset 0   magic      4 bytes   b"\\x89HWC"
    offset 4   version    u8        1
    offset 5   bom        u16       0x0102 (wire bytes \\x02\\x01); a
                                    reader that sees 0x0201 is looking at
                                    a byte-swapped frame and must reject
    offset 7   ncols      u16
    then per column, ncols times:
        name_len   u16
        name       utf-8 bytes
        kind       u8        0 = ndarray column, 1 = opaque bytes column
        kind 0:    dtype_len u8, dtype ascii (numpy str, e.g. "<f4"),
                   ndim u8, ndim x u32 dims, nbytes u64
        kind 1:    nbytes u64
    then all column buffers, contiguous, in column order.

The total frame length is validated exactly — both truncation and
trailing garbage fail closed with :class:`WireCodecError` naming the
byte offset. Array columns additionally validate
``nbytes == prod(dims) * itemsize``.

Content negotiation uses :data:`MEDIA_TYPE`
(``application/x-hops-packed``). JSON stays the default everywhere; the
packed path is opt-in per request (``Content-Type`` on the way in,
``Accept`` on the way out) and per shard (advertised in the shardd
healthz handshake).

On top of the frame, three payload shapes:

- predict requests/responses — a single tensor column
  (:func:`encode_instances` / :func:`decode_instances` /
  :func:`try_encode_predictions` / :func:`decode_predictions`);
- feature row batches — one numpy column per feature plus a reserved
  presence column, with a JSON-bytes fallback column for
  non-columnar batches (:func:`encode_rows` / :func:`decode_rows`);
- single kvstore rows — a compact struct-packed record behind the
  ``"\\x01"`` format byte (:func:`pack_row` / :func:`unpack_row`),
  latin-1-decoded so it rides the existing str-valued backends and
  coexists with legacy JSON rows in the same ``.hkv``/``.db`` file.
"""

from __future__ import annotations

import json
import struct
import time
from typing import Any, Sequence

import numpy as np

from hops_tpu.telemetry.metrics import REGISTRY

__all__ = [
    "MEDIA_TYPE",
    "MAGIC",
    "VERSION",
    "ROW_FORMAT_PACKED",
    "WireCodecError",
    "is_packed",
    "is_packed_row",
    "encode_frame",
    "decode_frame",
    "frame_summary",
    "encode_instances",
    "decode_instances",
    "try_encode_predictions",
    "decode_predictions",
    "encode_rows",
    "decode_rows",
    "pack_row",
    "unpack_row",
    "count_request",
]

#: Media type used for Content-Type / Accept negotiation.
MEDIA_TYPE = "application/x-hops-packed"

MAGIC = b"\x89HWC"
VERSION = 1

#: Byte-order mark: written as the little-endian u16 0x0102 (wire bytes
#: ``\x02\x01``). A reader that decodes 0x0201 is on the wrong end of a
#: byte-swapped frame.
_BOM = 0x0102
_BOM_SWAPPED = 0x0201

_HDR = struct.Struct("<4sBHH")  # magic, version, bom, ncols

_KIND_ARRAY = 0
_KIND_BYTES = 1

#: Column names starting with NUL are reserved for codec-internal
#: columns; user data never collides because real feature/column names
#: are printable.
_COL_PRESENT = "\x00present"
_COL_ROWS_JSON = "\x00rows"

#: Format byte prefix for packed single-row kvstore values. Legacy rows
#: are JSON objects and always start with ``{``, so a one-character
#: sniff disambiguates.
ROW_FORMAT_PACKED = "\x01"

#: numpy dtype strings allowed on the wire — little-endian or
#: byte-order-free numeric/bool types only. bf16 travels as ``<u2``
#: (the caller views/reinterprets); object/str columns are rejected.
_WIRE_DTYPES = frozenset({
    "<f8", "<f4", "<f2",
    "<i8", "<i4", "<i2", "|i1",
    "<u8", "<u4", "<u2", "|u1",
    "|b1",
})

# Children bound once at import — observe() on the hot path skips the
# per-call label lookup.
_ENCODE_SECONDS = REGISTRY.histogram(
    "hops_tpu_wire_encode_seconds",
    "Wall time spent encoding packed wire frames.",
).labels()
_DECODE_SECONDS = REGISTRY.histogram(
    "hops_tpu_wire_decode_seconds",
    "Wall time spent decoding packed wire frames.",
).labels()
_REQUESTS_TOTAL = REGISTRY.counter(
    "hops_tpu_wire_requests_total",
    "Predict requests by wire format.",
    labels=("format",),
)


class WireCodecError(ValueError):
    """A frame failed encode/decode validation.

    Decode-side messages name the byte offset where validation failed so
    truncation and corruption are diagnosable from the error alone.
    """


def count_request(fmt: str) -> None:
    """Count one predict request decoded in wire format ``fmt``."""
    _REQUESTS_TOTAL.labels(format=fmt).inc()


def is_packed(data: bytes | bytearray | memoryview | None) -> bool:
    """Cheap sniff: does ``data`` start with the packed-frame magic?"""
    return data is not None and bytes(data[:4]) == MAGIC


def is_packed_row(raw: str | None) -> bool:
    """Does a stored kvstore row value use the packed single-row format?"""
    return bool(raw) and raw[0] == ROW_FORMAT_PACKED


# ---------------------------------------------------------------------------
# frame encode / decode


def encode_frame(columns: Sequence[tuple[str, Any]]) -> bytes:
    """Encode named columns into one packed frame.

    Each column value is either an ndarray-convertible (becomes a kind-0
    tensor column; must land on a wire dtype) or ``bytes``/``bytearray``
    /``memoryview`` (kind-1 opaque bytes column). Big-endian arrays are
    byte-swapped to little-endian; non-contiguous arrays are made
    contiguous. Raises :class:`WireCodecError` for un-encodable dtypes
    (object/str — i.e. ragged or mixed columns).
    """
    t0 = time.perf_counter()
    if len(columns) > 0xFFFF:
        raise WireCodecError(f"too many columns: {len(columns)} > 65535")
    head: list[bytes] = [_HDR.pack(MAGIC, VERSION, _BOM, len(columns))]
    bufs: list[bytes] = []
    for name, col in columns:
        nb = name.encode("utf-8")
        if len(nb) > 0xFFFF:
            raise WireCodecError(f"column name too long: {len(nb)} bytes")
        head.append(struct.pack("<H", len(nb)))
        head.append(nb)
        if isinstance(col, (bytes, bytearray, memoryview)):
            raw = bytes(col)
            head.append(struct.pack("<BQ", _KIND_BYTES, len(raw)))
            bufs.append(raw)
            continue
        arr = col if isinstance(col, np.ndarray) else np.asarray(col)
        if not arr.flags.c_contiguous:
            # ascontiguousarray would promote 0-d to 1-d, but 0-d is
            # always contiguous so it never reaches this branch.
            arr = np.ascontiguousarray(arr)
        if arr.dtype.byteorder == ">":
            arr = arr.astype(arr.dtype.newbyteorder("<"))
        dt = arr.dtype.str.encode("ascii")
        if arr.dtype.str not in _WIRE_DTYPES:
            raise WireCodecError(
                f"column {name!r} has dtype {arr.dtype.str!r} which is not "
                f"wire-encodable (ragged/object/str columns cannot be packed)")
        if arr.ndim > 0xFF:
            raise WireCodecError(f"column {name!r} has ndim {arr.ndim} > 255")
        head.append(struct.pack("<BB", _KIND_ARRAY, len(dt)))
        head.append(dt)
        head.append(struct.pack("<B", arr.ndim))
        if arr.ndim:
            for dim in arr.shape:
                if dim > 0xFFFFFFFF:
                    raise WireCodecError(
                        f"column {name!r} dim {dim} exceeds u32")
            head.append(struct.pack(f"<{arr.ndim}I", *arr.shape))
        head.append(struct.pack("<Q", arr.nbytes))
        bufs.append(arr.tobytes())
    out = b"".join(head) + b"".join(bufs)
    _ENCODE_SECONDS.observe(time.perf_counter() - t0)
    return out


def _need(data: bytes, off: int, n: int, what: str) -> None:
    if off + n > len(data):
        raise WireCodecError(
            f"frame truncated at offset {off}: need {n} byte(s) for {what}, "
            f"have {len(data) - off}")


def _decode_headers(
    data: bytes,
) -> tuple[list[tuple[str, int, str, tuple[int, ...], int]], int]:
    """Parse frame headers only. Returns
    ``([(name, kind, dtype, dims, nbytes)], buffers_start_offset)``.

    Validates magic/version/BOM and per-column header integrity, plus
    the exact total frame length (buffers must be fully present with no
    trailing bytes).
    """
    _need(data, 0, _HDR.size, "frame header")
    magic, version, bom, ncols = _HDR.unpack_from(data, 0)
    if magic != MAGIC:
        raise WireCodecError(
            f"bad magic at offset 0: {magic!r} (not a packed frame)")
    if version != VERSION:
        raise WireCodecError(
            f"unsupported frame version {version} at offset 4 "
            f"(this reader speaks version {VERSION})")
    if bom == _BOM_SWAPPED:
        raise WireCodecError(
            "byte-order mark at offset 5 reads 0x0201: frame was written "
            "by a big-endian encoder; this reader only accepts "
            "little-endian frames")
    if bom != _BOM:
        raise WireCodecError(
            f"bad byte-order mark 0x{bom:04x} at offset 5 "
            f"(expected 0x{_BOM:04x})")
    off = _HDR.size
    cols: list[tuple[str, int, str, tuple[int, ...], int]] = []
    seen: set[str] = set()
    for i in range(ncols):
        _need(data, off, 2, f"column {i} name length")
        (name_len,) = struct.unpack_from("<H", data, off)
        off += 2
        _need(data, off, name_len, f"column {i} name")
        try:
            name = data[off:off + name_len].decode("utf-8")
        except UnicodeDecodeError as exc:
            raise WireCodecError(
                f"column {i} name at offset {off} is not valid utf-8: "
                f"{exc}") from None
        off += name_len
        if name in seen:
            raise WireCodecError(
                f"duplicate column name {name!r} at offset {off}")
        seen.add(name)
        _need(data, off, 1, f"column {name!r} kind")
        kind = data[off]
        off += 1
        if kind == _KIND_BYTES:
            _need(data, off, 8, f"column {name!r} byte length")
            (nbytes,) = struct.unpack_from("<Q", data, off)
            off += 8
            cols.append((name, kind, "", (), nbytes))
            continue
        if kind != _KIND_ARRAY:
            raise WireCodecError(
                f"column {name!r} has unknown kind {kind} at offset "
                f"{off - 1}")
        _need(data, off, 1, f"column {name!r} dtype length")
        dt_len = data[off]
        off += 1
        _need(data, off, dt_len, f"column {name!r} dtype")
        dtype = data[off:off + dt_len].decode("ascii", "replace")
        if dtype not in _WIRE_DTYPES:
            raise WireCodecError(
                f"column {name!r} dtype {dtype!r} at offset {off} is not "
                f"an accepted little-endian wire dtype")
        off += dt_len
        _need(data, off, 1, f"column {name!r} ndim")
        ndim = data[off]
        off += 1
        _need(data, off, 4 * ndim, f"column {name!r} dims")
        dims = struct.unpack_from(f"<{ndim}I", data, off) if ndim else ()
        off += 4 * ndim
        _need(data, off, 8, f"column {name!r} byte length")
        (nbytes,) = struct.unpack_from("<Q", data, off)
        off += 8
        count = 1
        for dim in dims:
            count *= dim
        expect = count * np.dtype(dtype).itemsize
        if nbytes != expect:
            raise WireCodecError(
                f"column {name!r} header at offset {off - 8} declares "
                f"{nbytes} bytes but shape {tuple(dims)} x dtype {dtype} "
                f"needs {expect}")
        cols.append((name, kind, dtype, tuple(dims), nbytes))
    total = off + sum(c[4] for c in cols)
    if len(data) < total:
        raise WireCodecError(
            f"frame truncated at offset {len(data)}: headers promise "
            f"{total} total bytes")
    if len(data) > total:
        raise WireCodecError(
            f"{len(data) - total} trailing byte(s) after offset {total}")
    return cols, off


def decode_frame(data: bytes | bytearray | memoryview) -> dict[str, Any]:
    """Decode a packed frame into ``{name: ndarray | bytes}``.

    Array columns are zero-copy views over ``data`` (via
    ``np.frombuffer``) and therefore read-only; callers that mutate must
    copy. Column order is preserved. Raises :class:`WireCodecError` on
    any malformation, naming the byte offset.
    """
    t0 = time.perf_counter()
    data = bytes(data) if not isinstance(data, bytes) else data
    cols, off = _decode_headers(data)
    out: dict[str, Any] = {}
    for name, kind, dtype, dims, nbytes in cols:
        if kind == _KIND_BYTES:
            out[name] = data[off:off + nbytes]
        else:
            dt = np.dtype(dtype)
            arr = np.frombuffer(data, dtype=dt,
                                count=nbytes // dt.itemsize, offset=off)
            out[name] = arr.reshape(dims)
        off += nbytes
    _DECODE_SECONDS.observe(time.perf_counter() - t0)
    return out


def frame_summary(data: bytes | bytearray | memoryview) -> dict[str, Any]:
    """Header-only summary of a packed frame — no buffer decode.

    Shape mirrors the workload-capture payload summary so armed capture
    on packed-body fleets records shapes instead of decode warnings::

        {"bytes": N, "format": "packed",
         "columns": [{"name", "dtype", "shape"} | {"name", "bytes"}]}
    """
    data = bytes(data) if not isinstance(data, bytes) else data
    cols, _ = _decode_headers(data)
    summary: dict[str, Any] = {
        "bytes": len(data), "format": "packed", "columns": []}
    for name, kind, dtype, dims, nbytes in cols:
        if kind == _KIND_BYTES:
            summary["columns"].append({"name": name, "bytes": nbytes})
        else:
            summary["columns"].append(
                {"name": name, "dtype": dtype, "shape": list(dims)})
    return summary


# ---------------------------------------------------------------------------
# predict bodies: a single tensor column


def encode_instances(instances: Any) -> bytes:
    """Encode a predict-request instance batch as one tensor column."""
    arr = instances if isinstance(instances, np.ndarray) else None
    if arr is None:
        try:
            arr = np.asarray(instances)
        except (ValueError, TypeError) as exc:
            raise WireCodecError(
                f"instances are not a rectangular tensor: {exc}") from None
    if arr.dtype == object:
        raise WireCodecError(
            "instances are ragged or non-numeric and cannot be packed")
    return encode_frame([("instances", arr)])


def decode_instances(data: bytes | bytearray | memoryview) -> np.ndarray:
    """Decode a packed predict request; returns the instance tensor."""
    cols = decode_frame(data)
    arr = cols.get("instances")
    if not isinstance(arr, np.ndarray):
        raise WireCodecError(
            "packed predict request must carry an 'instances' tensor column")
    return arr


def try_encode_predictions(preds: Any) -> bytes | None:
    """Encode a predictions payload, or ``None`` if it cannot be packed.

    ``None`` (ragged rows, object dtypes, non-tensor payloads) tells the
    caller to fall back to JSON — exactness over format. Natural dtype is
    preserved: ``.tolist()`` outputs become f64 columns so the packed
    response is bit-identical to what JSON would have carried.
    """
    try:
        arr = preds if isinstance(preds, np.ndarray) else np.asarray(preds)
    except (ValueError, TypeError):
        return None
    if arr.dtype == object:
        return None
    if arr.dtype.byteorder == ">":
        arr = arr.astype(arr.dtype.newbyteorder("<"))
    if arr.dtype.str not in _WIRE_DTYPES:
        return None
    return encode_frame([("predictions", arr)])


def decode_predictions(data: bytes | bytearray | memoryview) -> np.ndarray:
    """Decode a packed predict response; returns the prediction tensor."""
    cols = decode_frame(data)
    arr = cols.get("predictions")
    if not isinstance(arr, np.ndarray):
        raise WireCodecError(
            "packed predict response must carry a 'predictions' tensor "
            "column")
    return arr


# ---------------------------------------------------------------------------
# feature row batches: columnar dict-of-rows


def _column_array(vals: list[Any]) -> np.ndarray | None:
    """Type a row-batch column, or ``None`` if it is not numeric-uniform.

    Plain-Python/NumPy scalars only — bool before int (bool is an int
    subclass), and exact float64/int64 so the decode round-trips the
    original values bit-for-bit.
    """
    if all(isinstance(v, (bool, np.bool_)) for v in vals):
        return np.asarray(vals, dtype=np.bool_)
    if all(isinstance(v, (int, np.integer))
           and not isinstance(v, (bool, np.bool_)) for v in vals):
        try:
            return np.asarray([int(v) for v in vals], dtype=np.int64)
        except OverflowError:
            return None
    if all(isinstance(v, (float, np.floating)) for v in vals):
        return np.asarray([float(v) for v in vals], dtype=np.float64)
    return None


def encode_rows(rows: Sequence[dict | None]) -> bytes:
    """Encode a ``multi_get``-style row batch columnar.

    ``None`` entries (missing keys) travel in a reserved presence
    column. Homogeneous batches get one column per feature — numeric
    columns as typed arrays, everything else as a JSON-bytes column.
    Batches whose rows disagree on key sets fall back to a single
    JSON-bytes column; either way :func:`decode_rows` returns exactly
    what ``json.loads`` of the JSON encoding would have.
    """
    present = [r for r in rows if r is not None]
    mask = np.fromiter((r is not None for r in rows), dtype=np.bool_,
                       count=len(rows))
    names = list(present[0].keys()) if present else []
    homogeneous = (
        present
        and not any(n.startswith("\x00") for n in names)
        and all(set(r.keys()) == set(names) for r in present[1:])
    )
    cols: list[tuple[str, Any]] = [(_COL_PRESENT, mask)]
    if not present:
        return encode_frame(cols)
    if not homogeneous:
        blob = json.dumps(list(rows), default=str,
                          separators=(",", ":")).encode("utf-8")
        cols.append((_COL_ROWS_JSON, blob))
        return encode_frame(cols)
    for name in names:
        vals = [r[name] for r in present]
        arr = _column_array(vals)
        if arr is not None:
            cols.append((name, arr))
        else:
            blob = json.dumps(vals, default=str,
                              separators=(",", ":")).encode("utf-8")
            cols.append((name, blob))
    return encode_frame(cols)


def decode_rows(data: bytes | bytearray | memoryview) -> list[dict | None]:
    """Decode a packed row batch back into ``list[dict | None]``."""
    cols = decode_frame(data)
    if _COL_PRESENT not in cols:
        raise WireCodecError(
            "packed row batch is missing its presence column")
    mask = cols.pop(_COL_PRESENT)
    if not isinstance(mask, np.ndarray) or mask.dtype != np.bool_ \
            or mask.ndim != 1:
        raise WireCodecError(
            "packed row batch presence column must be a 1-d bool array")
    if _COL_ROWS_JSON in cols:
        rows = json.loads(bytes(cols[_COL_ROWS_JSON]))
        if len(rows) != len(mask):
            raise WireCodecError(
                f"packed row batch fallback carries {len(rows)} rows but "
                f"presence declares {len(mask)}")
        return rows
    n_present = int(mask.sum())
    names: list[str] = []
    series: list[list[Any]] = []
    for name, col in cols.items():
        vals = col.tolist() if isinstance(col, np.ndarray) \
            else json.loads(bytes(col))
        if len(vals) != n_present:
            raise WireCodecError(
                f"packed row batch column {name!r} carries {len(vals)} "
                f"values but presence declares {n_present}")
        names.append(name)
        series.append(vals)
    if series:
        built = iter([dict(zip(names, t)) for t in zip(*series)])
    else:
        built = iter([{} for _ in range(n_present)])
    return [next(built) if p else None for p in mask.tolist()]


# ---------------------------------------------------------------------------
# single kvstore rows: compact struct records behind a format byte


def pack_row(rec: dict) -> str:
    """Pack one feature row into the kvstore's str value space.

    Layout after the ``"\\x01"`` format byte (latin-1-decoded binary so
    it survives the str-valued backends and the utf-8 round trip to
    disk): u16 ncols, then per column u16 key_len + key, one typecode
    byte, and a typed payload —

    ``f`` f64 · ``i`` i64 · ``s`` u32 len + utf-8 · ``T``/``F`` bool ·
    ``n`` None · ``j`` u32 len + JSON (lists, timestamps, big ints; the
    same ``default=str`` coercion the legacy JSON rows used).
    """
    parts = [struct.pack("<H", len(rec))]
    for k, v in rec.items():
        kb = str(k).encode("utf-8")
        parts.append(struct.pack("<H", len(kb)))
        parts.append(kb)
        if v is None:
            parts.append(b"n")
        elif isinstance(v, (bool, np.bool_)):
            parts.append(b"T" if v else b"F")
        elif isinstance(v, (int, np.integer)) \
                and -(1 << 63) <= int(v) < (1 << 63):
            parts.append(b"i" + struct.pack("<q", int(v)))
        elif isinstance(v, (float, np.floating)):
            parts.append(b"f" + struct.pack("<d", float(v)))
        elif isinstance(v, str):
            sb = v.encode("utf-8")
            parts.append(b"s" + struct.pack("<I", len(sb)) + sb)
        else:
            jb = json.dumps(v, default=str,
                            separators=(",", ":")).encode("utf-8")
            parts.append(b"j" + struct.pack("<I", len(jb)) + jb)
    return ROW_FORMAT_PACKED + b"".join(parts).decode("latin-1")


def unpack_row(raw: str) -> dict:
    """Decode a :func:`pack_row` value back into the original dict."""
    if not is_packed_row(raw):
        raise WireCodecError("value does not carry the packed-row format "
                             "byte")
    data = raw[1:].encode("latin-1")
    _need(data, 0, 2, "row column count")
    (ncols,) = struct.unpack_from("<H", data, 0)
    off = 2
    rec: dict[str, Any] = {}
    for i in range(ncols):
        _need(data, off, 2, f"row column {i} key length")
        (klen,) = struct.unpack_from("<H", data, off)
        off += 2
        _need(data, off, klen, f"row column {i} key")
        key = data[off:off + klen].decode("utf-8")
        off += klen
        _need(data, off, 1, f"row column {key!r} typecode")
        code = data[off:off + 1]
        off += 1
        if code == b"n":
            rec[key] = None
        elif code == b"T":
            rec[key] = True
        elif code == b"F":
            rec[key] = False
        elif code == b"i":
            _need(data, off, 8, f"row column {key!r} i64")
            (rec[key],) = struct.unpack_from("<q", data, off)
            off += 8
        elif code == b"f":
            _need(data, off, 8, f"row column {key!r} f64")
            (rec[key],) = struct.unpack_from("<d", data, off)
            off += 8
        elif code in (b"s", b"j"):
            _need(data, off, 4, f"row column {key!r} length")
            (vlen,) = struct.unpack_from("<I", data, off)
            off += 4
            _need(data, off, vlen, f"row column {key!r} value")
            chunk = data[off:off + vlen]
            off += vlen
            rec[key] = (chunk.decode("utf-8") if code == b"s"
                        else json.loads(chunk))
        else:
            raise WireCodecError(
                f"row column {key!r} has unknown typecode {code!r} at "
                f"offset {off - 1}")
    if off != len(data):
        raise WireCodecError(
            f"{len(data) - off} trailing byte(s) after offset {off} in "
            f"packed row")
    return rec
