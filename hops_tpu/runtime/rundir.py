"""Per-run directory manager.

Implements the contract of the reference's ``hops.tensorboard.logdir()``
(reference: notebooks/ml/Experiment/Tensorflow/mnist.ipynb:55-61,
SURVEY.md §2.3): every experiment run gets a directory that serves as
log dir, checkpoint dir and working dir, is exposed to the user's
wrapper function while it runs, and is durably synced into the project's
``Experiments`` dataset when the run ends.

Run ids follow the reference's ``<app_id>_<run_number>`` shape, with the
Spark application id replaced by a session id.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import shutil
import tempfile
import threading
import time
import uuid
from pathlib import Path
from typing import Iterator

from hops_tpu.runtime import fs

_session_id: str | None = None
_run_counter = 0
# Per-context (thread/task) stack so concurrent trials each see their own
# active run; a fresh thread starts with an empty stack.
_active: contextvars.ContextVar[tuple["RunDir", ...]] = contextvars.ContextVar(
    "hops_tpu_active_runs", default=()
)
_state_lock = threading.Lock()
_live_activations = 0


def session_id() -> str:
    """Stable per-process session id (the reference's YARN app id).

    On a multi-host slice every host must agree on the id so run
    artifacts land in one shared directory — ``multihost.initialize``
    broadcasts the chief's id via :func:`set_session_id`, and the
    ``HOPS_TPU_SESSION_ID`` env var lets an external launcher pin it.
    """
    global _session_id
    if _session_id is None:
        _session_id = os.environ.get(
            "HOPS_TPU_SESSION_ID", f"application_{int(time.time())}_{uuid.uuid4().hex[:6]}"
        )
    return _session_id


def set_session_id(sid: str | None) -> None:
    global _session_id
    _session_id = sid


def experiments_root() -> Path:
    p = Path(fs.project_path("Experiments"))
    p.mkdir(parents=True, exist_ok=True)
    return p


class RunDir:
    """A single run's working directory.

    ``local_logdir=True`` mirrors the reference knob of the same name
    (PyTorch mnist.ipynb:251): work on fast local disk, upload to the
    Experiments dataset afterwards. ``False`` writes directly into the
    Experiments dataset.
    """

    def __init__(self, run_id: str, local_logdir: bool = False):
        self.run_id = run_id
        self.final_path = experiments_root() / run_id
        if local_logdir:
            self._work = Path(tempfile.mkdtemp(prefix=f"hops_tpu_{run_id}_"))
        else:
            self.final_path.mkdir(parents=True, exist_ok=True)
            self._work = self.final_path
        self.local_logdir = local_logdir
        self._finalized = False

    @property
    def logdir(self) -> str:
        return str(self._work)

    @property
    def checkpoint_dir(self) -> str:
        p = self._work / "checkpoints"
        p.mkdir(exist_ok=True)
        return str(p)

    def finalize(self) -> str:
        """Sync to the Experiments dataset; returns the durable path.
        Idempotent — a second call is a no-op."""
        if not self._finalized and self.local_logdir and self._work != self.final_path:
            self.final_path.mkdir(parents=True, exist_ok=True)
            shutil.copytree(self._work, self.final_path, dirs_exist_ok=True)
            shutil.rmtree(self._work, ignore_errors=True)
        self._finalized = True
        return str(self.final_path)


def new_run(name: str = "run", local_logdir: bool = False) -> RunDir:
    global _run_counter
    with _state_lock:
        _run_counter += 1
        n = _run_counter
    return RunDir(f"{session_id()}_{n}", local_logdir=local_logdir)


def logdir() -> str:
    """The active run's log/checkpoint/working dir — valid only inside a
    launched wrapper function (reference: ``tensorboard.logdir()``)."""
    stack = _active.get()
    if stack:
        return stack[-1].logdir
    # Outside a run (interactive use): fall back to a scratch dir, like
    # the reference did when called outside an experiment.
    scratch = Path(tempfile.gettempdir()) / "hops_tpu_scratch"
    scratch.mkdir(exist_ok=True)
    return str(scratch)


@contextlib.contextmanager
def activate(run: RunDir) -> Iterator[RunDir]:
    """Make ``run`` the current run for ``logdir()`` lookups.

    The process cwd is switched into the run dir (so relative writes get
    synced) only for the first concurrent activation — cwd is
    process-global, so under the parallel trial driver only ``logdir()``
    is a reliable base; concurrent trials keep the outer cwd.
    """
    global _live_activations
    token = _active.set(_active.get() + (run,))
    prev_cwd = os.getcwd()
    did_chdir = False
    with _state_lock:
        # Claim the cwd only when NO other activation is live — otherwise
        # a later trial would yank the cwd from under a running one.
        if _live_activations == 0:
            os.chdir(run.logdir)
            did_chdir = True
        _live_activations += 1
    try:
        yield run
    finally:
        _active.reset(token)
        with _state_lock:
            _live_activations -= 1
            if did_chdir:
                os.chdir(prev_cwd)
