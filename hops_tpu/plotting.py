"""Driver-side plotting: pull distributed results local, render figures.

The reference's Plotting suite (notebooks/ml/Plotting/
matplotlib_sparkmagic.ipynb:61,87,95) ships a cluster DataFrame to the
driver with ``%%spark -o df`` and plots it in ``%%local`` cells with
matplotlib. The TPU twin has no Livy hop to make: distributed results
already land driver-side as files — run metric streams
(``metrics.jsonl``, experiment/tensorboard.py), hyperparameter-search
summaries (``search/drivers.py``), and feature-group statistics
(``featurestore/statistics.py``). :func:`collect` is the ``-o df``
verb (everything becomes a pandas DataFrame on the driver); the
``plot_*`` helpers render the standard figures into the run dir
through matplotlib's Agg backend, so they work headless on a TPU host
exactly like the reference's ``%%local`` cells work on the Jupyter
driver.

No seaborn dependency: the environment pins to matplotlib, and every
figure here is a line/bar/histogram matplotlib draws directly.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

import numpy as np
import pandas as pd

import matplotlib

matplotlib.use("Agg", force=False)  # headless driver, like %%local on Jupyter
import matplotlib.pyplot as plt  # noqa: E402


def collect(source: Any) -> pd.DataFrame:
    """The ``%%spark -o df`` verb: pull a result set driver-local as a
    DataFrame.

    Accepts:
      * an experiment/run dir (or a ``metrics.jsonl`` path) — rows
        ``(step, tag, value, time)``;
      * a lagom result dict (``{"trials": {...}}`` from
        ``search.drivers.lagom``) — one row per trial with its params
        flattened as columns;
      * a ``FeatureGroup`` (anything with ``.read()``) — the group's
        rows, via its own offline read path;
      * a DataFrame (returned as-is) or anything ``pd.DataFrame``
        accepts (list of dicts, dict of columns).
    """
    if isinstance(source, pd.DataFrame):
        return source
    if hasattr(source, "read") and callable(source.read):
        return source.read()
    if isinstance(source, dict) and "trials" in source:
        rows = []
        for tid, t in source["trials"].items():
            row = {"trial": tid, "metric": t.get("metric")}
            row.update(t.get("params", {}))
            rows.append(row)
        return pd.DataFrame(rows)
    if isinstance(source, (str, Path)):
        from hops_tpu.runtime.logging import read_metrics

        path = Path(source)
        if path.is_dir():
            path = path / "metrics.jsonl"
        # read_metrics is the one reader for this stream (it tolerates
        # the torn tail line of a live run).
        return pd.DataFrame(read_metrics(path))
    return pd.DataFrame(source)


def _resolve_out(out: str | Path | None, default_name: str) -> Path:
    """Default figure destination: ``<active run dir>/plots/<name>``,
    the same place checkpoints and metric streams live — so a run's
    figures travel with the run, like the reference's HDFS
    ``Experiments`` dir artifacts."""
    if out is None:
        from hops_tpu.runtime import rundir

        out = Path(rundir.logdir()) / "plots" / default_name
    out = Path(out)
    out.parent.mkdir(parents=True, exist_ok=True)
    return out


def plot_metrics(
    run_dir: Any,
    tags: list[str] | None = None,
    out: str | Path | None = None,
    logy: bool = False,
) -> Path:
    """Line plots of a run's scalar stream, one panel per tag
    (loss curves, accuracy, throughput — whatever ``tensorboard.scalar``
    logged). Returns the PNG path."""
    df = collect(run_dir)
    if df.empty:
        raise ValueError(f"no metric events found in {run_dir!r}")
    tags = tags or sorted(df["tag"].unique())
    fig, axes = plt.subplots(
        len(tags), 1, figsize=(8, 2.6 * len(tags)), sharex=True, squeeze=False
    )
    for ax, tag in zip(axes[:, 0], tags):
        series = df[df["tag"] == tag].sort_values("step")
        ax.plot(series["step"], series["value"], lw=1.2)
        ax.set_ylabel(tag)
        if logy:
            ax.set_yscale("log")
        ax.grid(True, alpha=0.3)
    axes[-1, 0].set_xlabel("step")
    fig.suptitle(f"run metrics — {Path(str(run_dir)).name}")
    fig.tight_layout()
    out = _resolve_out(out, "metrics.png")
    fig.savefig(out, dpi=110)
    plt.close(fig)
    return out


def plot_statistics(
    stats_or_fg: Any,
    out: str | Path | None = None,
    max_features: int = 12,
) -> Path:
    """Feature-group statistics figure: per-feature mean ± stddev with
    min/max whiskers, plus histogram panels for features whose
    statistics config captured them. Accepts a statistics dict
    (``fg.get_statistics()`` / ``compute_statistics``) or a
    FeatureGroup (whose latest statistics are loaded). Returns the PNG
    path."""
    stats = stats_or_fg
    if hasattr(stats_or_fg, "get_statistics"):
        stats = stats_or_fg.get_statistics()
    feats = {
        name: e for name, e in (stats or {}).get("features", {}).items()
        if "mean" in e
    }
    if not feats:
        raise ValueError("no numeric feature statistics to plot "
                         "(is the group's statistics_config enabled?)")
    feats = dict(list(feats.items())[:max_features])
    hists = {n: e["histogram"] for n, e in feats.items() if "histogram" in e}

    n_hist_rows = -(-len(hists) // 3) if hists else 0
    fig = plt.figure(figsize=(9, 3.2 + 2.2 * n_hist_rows))
    gs = fig.add_gridspec(1 + n_hist_rows, 3)

    ax = fig.add_subplot(gs[0, :])
    names = list(feats)
    means = np.array([feats[n]["mean"] for n in names])
    stds = np.array([feats[n]["stddev"] for n in names])
    lows = np.array([feats[n]["min"] for n in names])
    highs = np.array([feats[n]["max"] for n in names])
    x = np.arange(len(names))
    ax.bar(x, means, yerr=stds, capsize=3, alpha=0.8)
    ax.vlines(x, lows, highs, color="gray", lw=1, alpha=0.6)
    ax.set_xticks(x)
    ax.set_xticklabels(names, rotation=30, ha="right", fontsize=8)
    ax.set_title(
        f"feature statistics — {stats.get('row_count', '?')} rows "
        "(bar: mean ± std, whisker: min–max)"
    )
    ax.grid(True, axis="y", alpha=0.3)

    for i, (name, h) in enumerate(hists.items()):
        hax = fig.add_subplot(gs[1 + i // 3, i % 3])
        edges = np.asarray(h["edges"])
        hax.bar(
            edges[:-1], h["counts"], width=np.diff(edges),
            align="edge", alpha=0.8,
        )
        hax.set_title(name, fontsize=9)
        hax.tick_params(labelsize=7)

    fig.tight_layout()
    out = _resolve_out(out, "statistics.png")
    fig.savefig(out, dpi=110)
    plt.close(fig)
    return out


def plot_trials(
    lagom_result: dict,
    out: str | Path | None = None,
) -> Path:
    """Hyperparameter-search convergence: per-trial metric in completion
    order with the best-so-far envelope — the figure the reference's
    maggy printed as a table (SURVEY.md §2.4). Returns the PNG path."""
    df = collect(lagom_result)
    if "metric" in df:
        df = df.dropna(subset=["metric"])  # failed trials have no score
    if df.empty or "metric" not in df:
        raise ValueError("no scored trials in lagom result")
    direction = str(lagom_result.get("direction", "max")).lower()
    vals = df["metric"].to_numpy(dtype=float)
    best = (
        np.maximum.accumulate(vals) if direction == "max"
        else np.minimum.accumulate(vals)
    )
    fig, ax = plt.subplots(figsize=(8, 4))
    ax.plot(np.arange(len(vals)), vals, "o", ms=4, alpha=0.7, label="trial")
    ax.plot(np.arange(len(vals)), best, lw=1.5, label=f"best so far ({direction})")
    ax.set_xlabel("trial (completion order)")
    ax.set_ylabel(lagom_result.get("metric_name", "metric"))
    ax.grid(True, alpha=0.3)
    ax.legend()
    ax.set_title(
        f"search — {lagom_result.get('num_trials', len(vals))} trials, "
        f"best {lagom_result.get('best_metric', best[-1]):.4g}"
    )
    fig.tight_layout()
    out = _resolve_out(out, "trials.png")
    fig.savefig(out, dpi=110)
    plt.close(fig)
    return out
