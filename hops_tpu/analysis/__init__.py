"""graftlint — AST-based JAX/TPU correctness linter for the hops_tpu tree.

The worst bugs in a traced-and-threaded codebase are invisible to
pytest on CPU: a silent dtype downcast inside a jitted step (PR 2), a
busy-spin in a lock acquire path (PR 3), a donated buffer read on the
next loop iteration that only explodes on a real device. This package
machine-checks those invariants: a rule engine over Python ASTs
(:mod:`.engine`), a findings/baseline model with justified suppressions
(:mod:`.model`, :mod:`.baseline`), six TPU/JAX-specific rules
(:mod:`.rules`), and a CLI (:mod:`.cli`, ``python -m hops_tpu.analysis``)
whose zero-findings exit code gates CI via
``tests/test_analysis_selfcheck.py``.

The analysis code itself is stdlib-only (``ast`` + ``tokenize`` — it
never imports JAX or touches a backend); note that running it as
``python -m hops_tpu.analysis`` still pays the parent package's import
cost, since ``-m`` imports ``hops_tpu`` first.

Quick use::

    from hops_tpu import analysis
    findings = analysis.lint([Path("hops_tpu")])
    for f in findings:
        print(f.render())
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterable

from hops_tpu.analysis.baseline import Baseline, BaselineError
from hops_tpu.analysis.engine import Context, Rule, all_rules, register, run
from hops_tpu.analysis.model import Finding, ParsedFile

__all__ = [
    "Baseline",
    "BaselineError",
    "Context",
    "Finding",
    "ParsedFile",
    "Rule",
    "all_rules",
    "lint",
    "register",
    "run",
]


def lint(
    paths: Iterable[Path | str],
    baseline: Path | str | None = None,
    docs_path: Path | str | None = None,
) -> list[Finding]:
    """Lint ``paths`` and return non-baselined findings — the in-process
    equivalent of the CLI (used by the tier-1 self-check test): same
    root resolution, same default docs discovery."""
    from hops_tpu.analysis import cli

    targets = [Path(p) for p in paths]
    root = cli.lint_root(targets)
    docs = Path(docs_path) if docs_path is not None else cli.default_docs(root)
    findings = run(targets, root=root, docs_path=docs)
    if baseline is not None:
        findings, _, _ = Baseline.load(Path(baseline)).split(findings)
    return findings
