"""graftlint CLI — ``python -m hops_tpu.analysis``.

Exit codes follow the CI contract: **0** clean (after baseline), **1**
non-baselined findings, **2** usage error (bad flags, unparsable
target, malformed/unjustified baseline). ``--format json`` emits the
machine schema the self-check test and external tooling consume;
``--write-baseline`` snapshots current findings with placeholder
justifications that the loader refuses until a human replaces them.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Sequence

from hops_tpu.analysis import baseline as baseline_mod
from hops_tpu.analysis import engine

JSON_SCHEMA_VERSION = 1

EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_USAGE = 2


def default_target() -> Path:
    """The installed ``hops_tpu`` package directory."""
    import hops_tpu

    return Path(hops_tpu.__file__).parent


def lint_root(paths: list[Path]) -> Path:
    """Directory finding paths are made relative to.

    When every target sits under the repo the ``hops_tpu`` package lives
    in, use that repo root — baseline entries then read
    ``hops_tpu/featurestore/loader.py`` regardless of which subtree was
    linted or where the CLI ran. Anything else (snippet dirs in tests)
    falls back to the targets' common ancestor.
    """
    repo = default_target().parent
    if all(p.resolve().is_relative_to(repo.resolve()) for p in paths):
        return repo
    return engine._common_root(paths)


def default_docs(root: Path) -> Path | None:
    """``docs/operations.md`` next to the lint root, if present."""
    for base in (root, root.parent):
        cand = base / "docs" / "operations.md"
        if cand.is_file():
            return cand
    return None


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m hops_tpu.analysis",
        description="graftlint: JAX/TPU correctness linter for the hops_tpu tree",
    )
    parser.add_argument(
        "paths", nargs="*", type=Path,
        help="files/directories to lint (default: the hops_tpu package)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="output format (default: text)",
    )
    parser.add_argument(
        "--baseline", type=Path, default=None,
        help="justified-findings baseline JSON to subtract (default: "
             "analysis_baseline.json at the lint root, when present)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore any default baseline file",
    )
    parser.add_argument(
        "--write-baseline", type=Path, default=None,
        help="write current findings as a baseline (placeholder "
             "justifications; fill them in before committing)",
    )
    parser.add_argument(
        "--docs", type=Path, default=None,
        help="operations doc for metric-name-consistency "
             "(default: docs/operations.md near the lint root)",
    )
    parser.add_argument(
        "--only", "--rules", dest="rules", default=None,
        help="comma-separated rule names to run (default: all); "
             "--rules is accepted as an alias",
    )
    parser.add_argument(
        "--changed", action="store_true",
        help="lint only files changed vs git HEAD (plus untracked); "
             "whole-program rules still analyze the full tree, with "
             "findings filtered to the changed files",
    )
    parser.add_argument(
        "--graph", choices=("lock",), default=None,
        help="dump the whole-program lock-acquisition graph instead of "
             "linting (DOT on text output, structured with --format json)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    return parser


def changed_paths(root: Path) -> "list[Path] | None":
    """``.py`` files changed vs HEAD plus untracked ones, or None when
    ``root`` is not a usable git checkout."""
    import subprocess

    names: set[str] = set()
    for args in (
        ["diff", "--name-only", "HEAD", "--"],
        ["ls-files", "--others", "--exclude-standard"],
    ):
        try:
            proc = subprocess.run(
                ["git", "-C", str(root), *args],
                capture_output=True, text=True, timeout=30,
            )
        except (OSError, subprocess.SubprocessError):
            return None
        if proc.returncode != 0:
            return None
        names.update(ln.strip() for ln in proc.stdout.splitlines() if ln.strip())
    return [
        root / n for n in sorted(names)
        if n.endswith(".py") and (root / n).is_file()
    ]


def main(argv: Sequence[str] | None = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)

    rules = engine.all_rules()
    if args.list_rules:
        for rule in rules:
            print(f"{rule.name}: {rule.description}")
        return EXIT_CLEAN

    if args.rules is not None:
        wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
        known = {r.name for r in rules}
        unknown = wanted - known
        if unknown:
            print(
                f"error: unknown rule(s): {', '.join(sorted(unknown))} "
                f"(known: {', '.join(sorted(known))})",
                file=sys.stderr,
            )
            return EXIT_USAGE
        rules = [r for r in rules if r.name in wanted]

    paths = args.paths or [default_target()]
    for p in paths:
        if not p.exists():
            print(f"error: no such lint target: {p}", file=sys.stderr)
            return EXIT_USAGE
    root = lint_root([Path(p) for p in paths])
    docs = args.docs if args.docs is not None else default_docs(root)
    if args.docs is not None and not args.docs.is_file():
        print(f"error: --docs file not found: {args.docs}", file=sys.stderr)
        return EXIT_USAGE

    if args.graph is not None:
        try:
            files = engine.parse_files([Path(p) for p in paths], root)
        except engine.ParseError as e:
            print(f"error: {e}", file=sys.stderr)
            return EXIT_USAGE
        from hops_tpu.analysis import concurrency
        from hops_tpu.analysis.project import ProjectIndex

        model = concurrency.ConcurrencyModel(ProjectIndex(files))
        if args.format == "json":
            print(json.dumps(model.graph_dict(), indent=2))
        else:
            print(model.graph_dot())
        return EXIT_CLEAN

    focus = None
    if args.changed:
        focus = changed_paths(root)
        if focus is None:
            print(
                f"error: --changed needs a git checkout at {root}",
                file=sys.stderr,
            )
            return EXIT_USAGE
        if not focus:
            if args.format == "json":
                print(json.dumps(report([], [], []), indent=2))
            else:
                print("0 finding(s) (no changed files)", file=sys.stderr)
            return EXIT_CLEAN

    try:
        findings = engine.run(
            paths, root=root, docs_path=docs, rules=rules, focus=focus
        )
    except engine.ParseError as e:
        print(f"error: {e}", file=sys.stderr)
        return EXIT_USAGE

    if args.write_baseline is not None:
        baseline_mod.write(args.write_baseline, findings)
        print(
            f"wrote {len(findings)} finding(s) to {args.write_baseline} — "
            "replace every placeholder justification before committing",
            file=sys.stderr,
        )

    baseline_path = args.baseline
    if args.write_baseline is not None:
        # A regeneration run reports the raw findings it just wrote;
        # subtracting the old (or the freshly written, still-placeholder)
        # baseline here would only obscure what went into the file.
        baseline_path = None
    elif baseline_path is None and not args.no_baseline:
        default_bl = root / "analysis_baseline.json"
        if default_bl.is_file():
            baseline_path = default_bl
    baselined: list = []
    stale: list[dict] = []
    if baseline_path is not None:
        try:
            bl = baseline_mod.Baseline.load(baseline_path)
        except baseline_mod.BaselineError as e:
            print(f"error: {e}", file=sys.stderr)
            return EXIT_USAGE
        findings, baselined, stale = bl.split(findings)
        if args.rules is not None or args.changed:
            # A subset run (--only, --changed) can't see the findings
            # the other rules' / other files' entries match — calling
            # them stale would tell the user to delete entries a full
            # run still needs.
            stale = []

    if args.format == "json":
        print(json.dumps(report(findings, baselined, stale), indent=2))
    else:
        for f in findings:
            print(f.render())
        if stale:
            print(
                f"warning: {len(stale)} stale baseline entrie(s) "
                f"(no matching finding) — delete them from the ledger:",
                file=sys.stderr,
            )
            for rule_name, entries in baseline_mod.group_stale(stale):
                print(f"  {rule_name}: {len(entries)}", file=sys.stderr)
                for e in entries:
                    print(
                        f"    {e['path']} [{e.get('symbol', '<module>')}]: "
                        f"{e['message']}",
                        file=sys.stderr,
                    )
        summary = f"{len(findings)} finding(s)"
        if baselined:
            summary += f", {len(baselined)} baselined"
        if stale:
            summary += f", {len(stale)} stale baseline entrie(s)"
        print(summary, file=sys.stderr)

    return EXIT_FINDINGS if findings else EXIT_CLEAN


def report(findings, baselined, stale) -> dict:
    """The ``--format json`` document."""
    by_rule: dict[str, int] = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    return {
        "version": JSON_SCHEMA_VERSION,
        "findings": [f.to_dict() for f in findings],
        "baselined": [f.to_dict() for f in baselined],
        "stale_baseline_entries": stale,
        "summary": {"count": len(findings), "by_rule": by_rule},
    }


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
