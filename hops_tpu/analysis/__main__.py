"""``python -m hops_tpu.analysis`` — run graftlint (see :mod:`.cli`).

The ``__name__`` guard matters: the import drift-guard sweep imports
this module as ``hops_tpu.analysis.__main__`` and must not trigger a
lint run with pytest's argv.
"""

import sys

from hops_tpu.analysis.cli import main

if __name__ == "__main__":
    try:
        code = main()
    except BrokenPipeError:
        # ``--graph lock | head`` closes stdout early; that's the
        # reader's choice, not an error worth a traceback.
        sys.stderr.close()
        code = 0
    sys.exit(code)
