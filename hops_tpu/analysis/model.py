"""Findings model and parsed-file representation for graftlint.

A :class:`Finding` is one rule violation anchored to a file/line; its
:attr:`Finding.fingerprint` deliberately excludes the line number so a
baseline entry survives unrelated edits above the finding (the classic
"baseline churn" failure of line-keyed suppression files). A
:class:`ParsedFile` bundles everything a rule needs — source, AST, and
the comment map that carries ``# graftlint: disable=`` pragmas and
``# guarded by:`` lock annotations — parsed once per file, shared by
every rule.

Stdlib-only (``ast`` + ``tokenize``): the analysis modules never import
JAX or initialize a backend of their own.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import re
import tokenize
from pathlib import Path

#: Inline suppression: ``# graftlint: disable=rule-a,rule-b`` on the
#: finding's line silences those rules for that line only.
_DISABLE_RE = re.compile(r"#\s*graftlint:\s*disable=([\w,\s-]+)")
#: Whole-file suppression: ``# graftlint: disable-file=rule-a`` anywhere
#: (conventionally in the module header).
_DISABLE_FILE_RE = re.compile(r"#\s*graftlint:\s*disable-file=([\w,\s-]+)")
#: Lock annotation: ``# guarded by: self._lock`` trailing an attribute
#: assignment (or a ``def`` line — the body then assumes the lock held).
_GUARDED_RE = re.compile(r"#\s*guarded by:\s*([\w.\[\]()'\" ]+?)\s*(?:#|$)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation.

    ``symbol`` is the enclosing def/class qualname (``<module>`` at top
    level): together with ``rule``, ``path`` and ``message`` it forms
    the line-number-free :attr:`fingerprint` baselines match on.
    """

    rule: str
    path: str  # posix-style, relative to the lint root
    line: int
    col: int
    message: str
    symbol: str = "<module>"
    #: Multi-line evidence (e.g. acquisition chains file:line by
    #: file:line). Excluded from the fingerprint — chains move with
    #: every unrelated edit, and a baseline keyed on them would churn
    #: exactly like a line-keyed one.
    detail: str = ""
    #: Other relpaths the finding's evidence spans (a cross-file
    #: inversion anchors on ONE acquisition site but implicates both).
    #: Engine-internal: ``--changed`` keeps a finding when any related
    #: file is in the changed set; not serialized, not fingerprinted.
    related: tuple = ()

    @property
    def fingerprint(self) -> str:
        h = hashlib.sha256(
            "\x1f".join((self.rule, self.path, self.symbol, self.message)).encode()
        )
        return h.hexdigest()[:16]

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "symbol": self.symbol,
            "fingerprint": self.fingerprint,
            "detail": self.detail,
        }

    def render(self) -> str:
        out = f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message} [{self.symbol}]"
        if self.detail:
            out += "".join(f"\n    {ln}" for ln in self.detail.splitlines())
        return out


class ParsedFile:
    """One source file, parsed once and shared by every rule."""

    def __init__(self, path: Path, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree = ast.parse(source, filename=str(path))
        #: line -> full comment text (including the ``#``).
        self.comments: dict[int, str] = {}
        #: line -> rule names disabled on that line.
        self.line_disables: dict[int, set[str]] = {}
        #: rule names disabled for the whole file.
        self.file_disables: set[str] = set()
        #: line -> lock expression from ``# guarded by:``.
        self.guard_comments: dict[int, str] = {}
        self._scan_comments()
        self._symbol_index: list[tuple[int, int, str]] | None = None
        self._parent_map: dict[int, ast.AST] | None = None

    def parents(self) -> dict[int, ast.AST]:
        """``id(child) -> parent`` for every node in the tree, built
        once per file and shared by every rule that walks ancestor
        chains (keyed by ``id`` because AST nodes are unhashable-by-
        value and identity is what an ancestor walk needs)."""
        if self._parent_map is None:
            pm: dict[int, ast.AST] = {}
            for n in ast.walk(self.tree):
                for child in ast.iter_child_nodes(n):
                    pm[id(child)] = n
            self._parent_map = pm
        return self._parent_map

    def _scan_comments(self) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                line = tok.start[0]
                self.comments[line] = tok.string
                m = _DISABLE_RE.search(tok.string)
                if m:
                    rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                    self.line_disables.setdefault(line, set()).update(rules)
                m = _DISABLE_FILE_RE.search(tok.string)
                if m:
                    self.file_disables.update(
                        r.strip() for r in m.group(1).split(",") if r.strip()
                    )
                m = _GUARDED_RE.search(tok.string)
                if m:
                    self.guard_comments[line] = m.group(1).strip()
        except tokenize.TokenError:
            pass  # ast.parse succeeded; comments best-effort

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_disables:
            return True
        return rule in self.line_disables.get(line, set())

    # -- symbol resolution ----------------------------------------------------

    def _build_symbol_index(self) -> list[tuple[int, int, str]]:
        """``(start, end, qualname)`` spans for every def/class, sorted
        outermost-first so the LAST containing span is the innermost."""
        spans: list[tuple[int, int, str]] = []

        def visit(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                ):
                    qual = f"{prefix}.{child.name}" if prefix else child.name
                    end = getattr(child, "end_lineno", child.lineno) or child.lineno
                    spans.append((child.lineno, end, qual))
                    visit(child, qual)
                else:
                    visit(child, prefix)

        visit(self.tree, "")
        spans.sort()
        return spans

    def symbol_at(self, line: int) -> str:
        """Qualname of the innermost def/class containing ``line``."""
        if self._symbol_index is None:
            self._symbol_index = self._build_symbol_index()
        best = "<module>"
        for start, end, qual in self._symbol_index:
            if start <= line <= end:
                best = qual
        return best

    def finding(
        self, rule: str, node: ast.AST, message: str, detail: str = ""
    ) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(
            rule=rule,
            path=self.relpath,
            line=line,
            col=col,
            message=message,
            symbol=self.symbol_at(line),
            detail=detail,
        )
