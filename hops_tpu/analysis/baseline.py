"""Justified-findings baseline for graftlint.

A baseline absorbs *known, accepted* findings so the CI gate can demand
zero NEW ones. Every entry must carry a human-written justification —
the file is a reviewable ledger of accepted debt, not a mute button.
Entries match findings by the line-number-free fingerprint
(rule + path + symbol + message), so unrelated edits above a finding
don't invalidate the baseline, while any change to the finding itself
(moved file, changed message, renamed enclosing function) surfaces it
again for re-justification.
"""

from __future__ import annotations

import json
from pathlib import Path

from hops_tpu.analysis.model import Finding

VERSION = 1

#: Placeholder ``--write-baseline`` emits; the loader rejects it so a
#: generated baseline cannot be merged without human justification.
TODO_JUSTIFICATION = "TODO: justify or fix"


class BaselineError(ValueError):
    """Malformed or unjustified baseline — a usage error (exit 2)."""


def _entry_fingerprint(entry: dict) -> str:
    return Finding(
        rule=entry["rule"],
        path=entry["path"],
        line=0,
        col=0,
        message=entry["message"],
        symbol=entry.get("symbol", "<module>"),
    ).fingerprint


class Baseline:
    """Loaded baseline: fingerprint -> entry (with multiplicity)."""

    def __init__(self, entries: list[dict]):
        self.entries = entries
        self.by_fingerprint: dict[str, list[dict]] = {}
        for e in entries:
            self.by_fingerprint.setdefault(_entry_fingerprint(e), []).append(e)

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        try:
            data = json.loads(Path(path).read_text())
        except FileNotFoundError:
            raise BaselineError(f"baseline file not found: {path}")
        except ValueError as e:
            raise BaselineError(f"baseline {path} is not valid JSON: {e}")
        if not isinstance(data, dict) or data.get("version") != VERSION:
            raise BaselineError(
                f"baseline {path}: expected {{'version': {VERSION}, 'entries': [...]}}"
            )
        entries = data.get("entries")
        if not isinstance(entries, list):
            raise BaselineError(f"baseline {path}: 'entries' must be a list")
        for i, e in enumerate(entries):
            for field in ("rule", "path", "message", "justification"):
                if not isinstance(e.get(field), str) or not e.get(field).strip():
                    raise BaselineError(
                        f"baseline {path}: entry {i} missing non-empty {field!r}"
                    )
            if e["justification"].strip() == TODO_JUSTIFICATION:
                raise BaselineError(
                    f"baseline {path}: entry {i} ({e['rule']} in {e['path']}) "
                    f"still carries the generated placeholder justification — "
                    f"write a real one or fix the finding"
                )
        return cls(entries)

    def split(
        self, findings: list[Finding]
    ) -> tuple[list[Finding], list[Finding], list[dict]]:
        """``(new, baselined, stale_entries)`` — stale entries matched no
        current finding and should be deleted from the file.

        Each entry absorbs at most ONE finding: fingerprints carry no
        line number, so a second identical violation appearing in the
        same symbol must surface as new, not vanish behind the entry
        that justified the first."""
        new: list[Finding] = []
        baselined: list[Finding] = []
        remaining = {fp: len(es) for fp, es in self.by_fingerprint.items()}
        for f in findings:
            if remaining.get(f.fingerprint, 0) > 0:
                remaining[f.fingerprint] -= 1
                baselined.append(f)
            else:
                new.append(f)
        stale = [
            e
            for fp, es in self.by_fingerprint.items()
            for e in es[: remaining.get(fp, 0)]
        ]
        return new, baselined, stale


def group_stale(stale: list[dict]) -> list[tuple[str, list[dict]]]:
    """Stale entries grouped by rule, biggest group first (ties break on
    rule name) — with one ledger spanning 17 rules, a flat list hides
    which rule's debt actually rotted."""
    groups: dict[str, list[dict]] = {}
    for e in stale:
        groups.setdefault(e.get("rule", "<unknown>"), []).append(e)
    return sorted(groups.items(), key=lambda kv: (-len(kv[1]), kv[0]))


def write(path: Path, findings: list[Finding]) -> None:
    """Emit a baseline holding ``findings``, merging with any existing
    file at ``path``: entries whose fingerprint still matches keep their
    human-written justification (regeneration must never reset accepted
    debt to placeholders), and existing entries with no matching finding
    are preserved too — a ``--rules``-subset or single-directory run
    cannot see the findings the rest of the ledger covers, so dropping
    them would silently destroy justified entries. Truly stale entries
    are reported by a full run's stale check and deleted by a human."""
    existing: dict[str, list[dict]] = {}
    try:
        old = json.loads(Path(path).read_text())
        for e in old.get("entries", []):
            if isinstance(e, dict) and all(
                isinstance(e.get(k), str) for k in ("rule", "path", "message")
            ):
                existing.setdefault(_entry_fingerprint(e), []).append(e)
    except (FileNotFoundError, ValueError):
        pass  # no previous ledger (or unreadable): start fresh
    entries = []
    for f in findings:
        matched = existing.get(f.fingerprint)
        justification = (
            matched.pop(0)["justification"]
            if matched and matched[0].get("justification", "").strip()
            not in ("", TODO_JUSTIFICATION)
            else TODO_JUSTIFICATION
        )
        entries.append(
            {
                "rule": f.rule,
                "path": f.path,
                "symbol": f.symbol,
                "message": f.message,
                "justification": justification,
            }
        )
    for leftover in existing.values():
        entries.extend(leftover)
    Path(path).write_text(
        json.dumps({"version": VERSION, "entries": entries}, indent=2) + "\n"
    )
