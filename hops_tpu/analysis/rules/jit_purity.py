"""jit-purity: Python side effects reachable inside traced functions.

``jax.jit``/``shard_map`` trace a function ONCE and replay the captured
XLA program; Python-level side effects inside the traced body run at
trace time only (or once per recompile) — so a ``print`` shows stale
values, ``time.time()`` freezes the timestamp of the first trace,
stdlib ``random`` draws one constant, a telemetry ``.inc()`` counts
compilations instead of steps, and a ``global`` write mutates host
state on a schedule nobody can predict. PR 2's silent fp32->bf16 param
downcast lived exactly here: a traced step quietly doing host-visible
work nobody could see in pytest.

A function is considered traced when it is:

- decorated with ``jit``/``jax.jit``/``pjit``/``shard_map`` (bare,
  called, or via ``functools.partial``),
- passed as the first argument to a ``jit(...)``/``shard_map(...)``
  call or a ``<strategy>.step(...)`` call, or
- defined inside (and thus returned by) a ``make_*step*`` factory —
  the ``make_train_step`` convention this repo compiles via
  ``Strategy.step``.

``jax.debug.print`` / ``jax.debug.callback`` / ``io_callback`` are the
sanctioned escape hatches and are not flagged.
"""

from __future__ import annotations

import ast
import re

from hops_tpu.analysis.engine import (
    Context,
    Rule,
    call_name,
    dotted_name,
    register,
)
from hops_tpu.analysis.model import Finding, ParsedFile

_JIT_NAMES = {"jit", "pjit", "shard_map"}
_FACTORY_RE = re.compile(r"^make\w*step\w*$")
_METRIC_MUTATORS = {"inc", "dec", "observe", "set_to_current_time"}
_METRIC_RECEIVER_RE = re.compile(
    r"(^|\.)(_?m_\w+|REGISTRY|registry|\w*(metric|counter|gauge|histogram)\w*)",
    re.IGNORECASE,
)


def _is_at_indexer(node: ast.AST) -> bool:
    """``x.at[i]`` — the receiver of JAX's pure functional-update
    ``.set()``/``.add()``, which must never read as a metric mutation
    even on an array named ``metrics``."""
    return (
        isinstance(node, ast.Subscript)
        and isinstance(node.value, ast.Attribute)
        and node.value.attr == "at"
    )


def _is_jit_expr(node: ast.AST) -> bool:
    """``jit`` / ``jax.jit`` / ``pjit`` / ``shard_map`` (possibly via
    ``partial(jax.jit, ...)``), as a decorator or call target."""
    if call_name(node) in _JIT_NAMES:
        return True
    if isinstance(node, ast.Call):
        if call_name(node.func) in _JIT_NAMES:
            return True
        if call_name(node.func) == "partial" and node.args:
            return _is_jit_expr(node.args[0])
    return False


def _collect_traced(tree: ast.Module) -> list[ast.FunctionDef]:
    """Function defs whose bodies will be traced."""
    by_name: dict[str, list[ast.FunctionDef]] = {}
    traced: dict[int, ast.FunctionDef] = {}

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, []).append(node)
            if any(_is_jit_expr(d) for d in node.decorator_list):
                traced[id(node)] = node
            if _FACTORY_RE.match(node.name):
                # Only the def(s) the factory RETURNS are traced; other
                # inner helpers run at factory (plain Python) time.
                returned = {
                    r.value.id
                    for r in ast.walk(node)
                    if isinstance(r, ast.Return) and isinstance(r.value, ast.Name)
                }
                for child in ast.walk(node):
                    if (
                        isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and child is not node
                        and child.name in returned
                    ):
                        traced[id(child)] = child

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        target: ast.AST | None = None
        if _is_jit_expr(node.func) and not isinstance(node.func, ast.Call):
            target = node.args[0] if node.args else None
        elif isinstance(node.func, ast.Attribute) and node.func.attr == "step":
            # <strategy>.step(fn, ...) compiles fn; only count plain
            # Name args that resolve to a local def (engine.step() and
            # friends take no function argument).
            target = node.args[0] if node.args else None
        if isinstance(target, ast.Name):
            for fn in by_name.get(target.id, ()):
                traced[id(fn)] = fn
        elif isinstance(target, (ast.FunctionDef, ast.Lambda)):
            pass  # lambdas have no statements worth flagging
    return list(traced.values())


@register
class JitPurityRule(Rule):
    name = "jit-purity"
    description = (
        "Python side effects (print, time.*, stdlib random, telemetry "
        "mutation, global writes) inside jit/shard_map-traced functions"
    )

    def check_file(self, pf: ParsedFile, ctx: Context) -> list[Finding]:
        # Only treat `time.*`/`random.*` as the stdlib modules when the
        # file actually imports them bare — otherwise `time` may be an
        # array argument (timestep code) and `random` a jax.random alias.
        std_imports = {
            a.name
            for n in ast.walk(pf.tree)
            if isinstance(n, ast.Import)
            for a in n.names
            if a.asname is None
        }
        findings: list[Finding] = []
        seen: set[tuple[int, int]] = set()
        for fn in _collect_traced(pf.tree):
            for node in ast.walk(fn):
                f = self._check_node(pf, fn, node, std_imports)
                if f is not None and (f.line, f.col) not in seen:
                    seen.add((f.line, f.col))
                    findings.append(f)
        return findings

    def _check_node(
        self,
        pf: ParsedFile,
        fn: ast.FunctionDef,
        node: ast.AST,
        std_imports: set[str],
    ) -> Finding | None:
        where = f"traced function `{fn.name}`"
        if isinstance(node, ast.Global):
            return pf.finding(
                self.name,
                node,
                f"`global {', '.join(node.names)}` write inside {where} "
                "mutates host state at trace time only; return the value "
                "or use jax.debug.callback",
            )
        if not isinstance(node, ast.Call):
            return None
        func = node.func
        if isinstance(func, ast.Name) and func.id == "print":
            return pf.finding(
                self.name,
                node,
                f"`print` inside {where} runs at trace time only; use "
                "jax.debug.print for runtime values",
            )
        dn = dotted_name(func)
        if "time" in std_imports and dn.startswith("time."):
            return pf.finding(
                self.name,
                node,
                f"`{dn}` inside {where} freezes the clock at trace time; "
                "take timestamps outside the step",
            )
        if "random" in std_imports and dn.startswith("random."):
            return pf.finding(
                self.name,
                node,
                f"stdlib `{dn}` inside {where} draws ONE value at trace "
                "time; thread a jax.random key instead",
            )
        if (
            isinstance(func, ast.Attribute)
            and (func.attr in _METRIC_MUTATORS or func.attr == "set")
            and not _is_at_indexer(func.value)
            and self._metric_receiver(func.value)
        ):
            recv = ast.unparse(func.value)
            return pf.finding(
                self.name,
                node,
                f"telemetry mutation `{recv}.{func.attr}(...)` inside "
                f"{where} counts trace-time compilations, not steps; "
                "update metrics outside the traced body",
            )
        return None

    @staticmethod
    def _metric_receiver(node: ast.AST) -> bool:
        try:
            text = ast.unparse(node)
        except Exception:  # pragma: no cover - unparse is total on exprs
            return False
        return bool(_METRIC_RECEIVER_RE.search(text))
