"""blocking-under-lock: a known-blocking operation reached while a
``threading`` lock is held.

A lock held across network I/O, ``subprocess``, ``time.sleep``,
``with_deadline``, kvstore FFI or ``fsync`` turns every peer of that
lock into a hostage of the slowest downstream dependency — the p99
amplifier behind most "everything got slow at once" serving incidents.
The check is interprocedural: ``self._flush()`` called under
``self._lock`` is traced into the blocking write it performs, using the
whole-program blocking summaries from
:mod:`hops_tpu.analysis.concurrency`.

The one sanctioned wait-under-lock is ``cv.wait()`` under ``with cv:``
— the wait *releases* that condition's lock, so holding it is the
consumer protocol, not a stall. Holding any OTHER lock across the wait
is still flagged.

Fix by shrinking the critical section: snapshot state under the lock,
do the slow work outside, re-take the lock to publish.
"""

from __future__ import annotations

from hops_tpu.analysis import concurrency
from hops_tpu.analysis.engine import Context, Rule, register
from hops_tpu.analysis.model import Finding, ParsedFile


@register
class BlockingUnderLockRule(Rule):
    name = "blocking-under-lock"
    description = (
        "a blocking operation (network, subprocess, sleep, FFI, fsync, "
        "foreign cv/event wait) reached while holding a lock"
    )

    def check_project(
        self, files: list[ParsedFile], ctx: Context
    ) -> list[Finding]:
        model = concurrency.get_model(files, ctx)
        by_path = {pf.relpath: pf for pf in files}
        findings: list[Finding] = []
        for hb in model.held_blocks():
            path, line, _ = hb.step
            pf = by_path.get(path)
            if pf is None:
                continue
            findings.append(
                Finding(
                    rule=self.name,
                    path=path,
                    line=line,
                    col=0,
                    message=(
                        f"blocking `{hb.block.label}` reached while holding "
                        f"`{hb.lock.id}` — move it outside the critical "
                        f"section or hand off to a worker"
                    ),
                    symbol=pf.symbol_at(line),
                    detail=concurrency._fmt_chain(hb.chain),
                    related=tuple(sorted(
                        {p for p, _, _ in hb.chain} - {path}
                    )),
                )
            )
        return findings
