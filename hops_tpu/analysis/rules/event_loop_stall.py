"""event-loop-stall: a transitively-blocking call reachable from a
selector IO loop.

The event-loop server core (``runtime/httpserver.py``) multiplexes
every connection on one thread around ``selector.select()``; anything
that sleeps, dials, forks or waits on that thread stalls ALL
connections at once — the worst failure mode a serving tier has. This
rule finds every selector loop in the tree (a class owning a
``selectors.DefaultSelector()`` attribute, rooted at the method that
calls ``.select()`` on it), walks the conservative call graph from the
root, and flags any blocking operation it can reach.

The sanctioned escape is worker-pool dispatch: parking the request on a
queue under a brief ``Condition`` notify and letting a worker thread
run the handler. Thread targets are not call-graph edges, so the
handoff pattern is structurally invisible to the traversal — exactly
the shape the loop is allowed to use. ``select()`` itself is the loop's
own wait and is never flagged.
"""

from __future__ import annotations

from hops_tpu.analysis import concurrency
from hops_tpu.analysis.engine import Context, Rule, register
from hops_tpu.analysis.model import Finding, ParsedFile


@register
class EventLoopStallRule(Rule):
    name = "event-loop-stall"
    description = (
        "a blocking operation reachable from a selector IO-loop thread "
        "(the loop must dispatch to workers instead)"
    )

    def check_project(
        self, files: list[ParsedFile], ctx: Context
    ) -> list[Finding]:
        model = concurrency.get_model(files, ctx)
        by_path = {pf.relpath: pf for pf in files}
        findings: list[Finding] = []
        for stall in model.loop_stalls():
            path, line, _ = stall.step
            pf = by_path.get(path)
            if pf is None:
                continue
            findings.append(
                Finding(
                    rule=self.name,
                    path=path,
                    line=line,
                    col=0,
                    message=(
                        f"blocking `{stall.block.label}` in "
                        f"`{stall.func.qualname}` is reachable on the "
                        f"selector IO loop rooted at `{stall.root.qualname}` "
                        f"— every connection stalls; dispatch to the worker "
                        f"pool instead"
                    ),
                    symbol=pf.symbol_at(line),
                    detail=concurrency._fmt_chain(stall.chain),
                    related=tuple(sorted(
                        {p for p, _, _ in stall.chain} - {path}
                    )),
                )
            )
        return findings
