"""hardcoded-loopback: no baked-in loopback URLs on multi-host paths.

The placement layer (``jobs/placement/``) made fleet replicas and
feature-store shards remotely placeable: every router→replica and
store→shard hop now derives its URL from the unit's registered
``host:port``. A literal ``http://127.0.0.1:...`` (or
``http://localhost...``) on one of those paths silently pins the hop to
the local machine — the fleet LOOKS healthy in single-host tests and
then routes every remote replica's traffic to the wrong host in
production. This rule makes that regression loud.

Flagged, on the multi-host serving paths only
(``modelrepo/fleet/`` and ``featurestore/online_serving.py``):

- any string literal that spells a URL at a loopback address — both
  ``http`` and a loopback host (``127.0.0.1`` / ``localhost`` /
  ``::1``) inside ONE literal. F-strings are covered through their
  constant fragments (``f"http://127.0.0.1:{port}"`` carries the
  fragment ``"http://127.0.0.1:"``).

NOT flagged: bare loopback literals with no scheme — bind addresses
(``ThreadingHTTPServer(("127.0.0.1", port), ...)``), defaults for
host fields, log strings. Binding a local server to loopback is
correct; only a URL hardcodes where a REQUEST goes. Deliberately
local hops (a router's own published endpoint) are baselined with a
justification in ``analysis_baseline.json``.
"""

from __future__ import annotations

import ast

from hops_tpu.analysis.engine import Context, Rule, register
from hops_tpu.analysis.model import Finding, ParsedFile

#: Path fragments that put a file in scope: the hops the placement
#: layer can route to a remote host.
SCOPE = (
    "hops_tpu/modelrepo/fleet/",
    "hops_tpu/featurestore/online_serving.py",
)

_LOOPBACK = ("127.0.0.1", "localhost", "::1")


def _is_loopback_url(value: str) -> bool:
    lower = value.lower()
    return "http" in lower and any(h in lower for h in _LOOPBACK)


@register
class HardcodedLoopbackRule(Rule):
    name = "hardcoded-loopback"
    description = (
        "loopback URL literal on a multi-host serving path — derive "
        "the address from the replica/shard registration (placement "
        "layer) instead of pinning the hop to the local machine"
    )

    def check_file(self, pf: ParsedFile, ctx: Context) -> list[Finding]:
        if not any(s in pf.relpath for s in SCOPE):
            return []
        findings: list[Finding] = []
        for node in ast.walk(pf.tree):
            # F-string fragments are ast.Constant children of
            # JoinedStr, so one Constant check covers both literal
            # shapes.
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            if _is_loopback_url(node.value):
                findings.append(
                    pf.finding(
                        self.name,
                        node,
                        "loopback URL literal on a multi-host path — "
                        "placed replicas/shards live on other hosts; "
                        "build the URL from the unit's registered "
                        "host:port",
                    )
                )
        return findings
