"""naked-retry-loop: retry loops sleeping a constant, with no backoff.

A ``while``/``for`` that catches an exception and ``time.sleep``\\ s a
*constant* before trying again is a retry storm waiting to happen: when
the dependency actually goes down, every worker in the fleet re-dogpiles
it in lockstep at exactly the same cadence (the AWS full-jitter result;
this is why ``runtime/resilience.py`` exists). The PR-3 relay-lock
incident was this exact shape — a ``FileExistsError`` busy-spin.

Flagged: a loop whose body contains a ``try``/``except`` (the retry
shape) AND a ``time.sleep(<constant>)`` / ``sleep(<constant>)`` call
anywhere inside the loop. Not flagged: poll/wait loops with no
exception handling (sleeping a constant while *watching* for a state
change is fine — nothing failed), computed sleeps (a
``RetryPolicy.delay(...)`` result is a Name, not a Constant), and the
sanctioned backoff homes ``runtime/resilience.py`` and
``runtime/relaylock.py``.

The fix is almost always ``resilience.RetryPolicy(...).call(fn)`` —
bounded attempts, exponential backoff, full jitter, telemetry.
"""

from __future__ import annotations

import ast

from hops_tpu.analysis.engine import Context, Rule, dotted_name, register
from hops_tpu.analysis.model import Finding, ParsedFile

#: Modules allowed to hand-roll sleeps in retry shapes: the policy
#: engine itself, and the relay lock's carefully-reviewed wait loop.
SANCTIONED = (
    "hops_tpu/runtime/resilience.py",
    "hops_tpu/runtime/relaylock.py",
)


def _is_sleep(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    return name in ("time.sleep", "sleep", "_time.sleep")


def _walk_in_loop(loop: ast.AST):
    """Walk a loop's subtree WITHOUT descending into nested def/lambda
    bodies: code there runs when the helper is *called*, not per loop
    iteration, so it is not this loop's retry behavior."""
    stack = [loop]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            stack.append(child)


def _constant_sleeps(loop: ast.AST) -> list[ast.Call]:
    return [
        n for n in _walk_in_loop(loop)
        if _is_sleep(n) and n.args and isinstance(n.args[0], ast.Constant)
    ]


def _has_handler(loop: ast.AST) -> bool:
    return any(
        isinstance(n, ast.Try) and n.handlers for n in _walk_in_loop(loop)
        if n is not loop
    )


@register
class NakedRetryLoopRule(Rule):
    name = "naked-retry-loop"
    description = (
        "retry loop (try/except inside while/for) sleeping a constant — "
        "no backoff or jitter; use runtime.resilience.RetryPolicy"
    )

    def check_file(self, pf: ParsedFile, ctx: Context) -> list[Finding]:
        if any(pf.relpath.endswith(s) for s in SANCTIONED):
            return []
        matches: list[ast.AST] = [
            node for node in ast.walk(pf.tree)
            if isinstance(node, (ast.While, ast.For))
            and _has_handler(node) and _constant_sleeps(node)
        ]
        findings = []
        for loop in matches:
            # Report the innermost matching loop only: an outer loop
            # wrapping a flagged inner one adds no information.
            if any(
                other is not loop and other in _walk_in_loop(loop)
                for other in matches
            ):
                continue
            sleep = _constant_sleeps(loop)[0]
            findings.append(
                pf.finding(
                    self.name,
                    sleep,
                    "retry loop sleeps a constant "
                    f"{sleep.args[0].value!r}s — a fleet retries in "
                    "lockstep; use resilience.RetryPolicy (exponential "
                    "backoff + full jitter) or justify inline",
                )
            )
        return findings
