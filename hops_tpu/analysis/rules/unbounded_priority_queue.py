"""unbounded-priority-queue: serving-tier priority queues declare a bound.

A priority queue without a bound is the quiet version of the overload
the shedders exist to prevent: under sustained pressure the low class
never drains, the queue grows without limit, and the process dies of
memory instead of answering 503s — with the added cruelty that every
queued batch item did its waiting for nothing. The QoS design
(``runtime/qos.py``, docs/operations.md "Tail latency & QoS") therefore
requires every priority queue in the serving tiers to declare a hard
bound and a shed policy (``qos.BoundedPriorityQueue`` is the sanctioned
shape: bound + shed-lowest-class-first + starvation guard).

Flagged, in the serving tiers only (``modelrepo/fleet/``,
``modelrepo/serving.py``, ``modelrepo/lm_engine.py``, and
``runtime/qos.py`` itself):

- ``queue.PriorityQueue(...)`` constructed with no ``maxsize`` (or a
  literal ``maxsize <= 0`` — the stdlib's "unbounded" spelling);
- ``BoundedPriorityQueue(...)`` constructed without a bound argument,
  or with a literal non-positive / ``None`` bound.

A bound passed as a name or expression is accepted (it is config; the
constructor validates positivity at runtime). Non-serving code (offline
tooling, tests) is out of scope — the failure mode being defended
against is serving-path memory collapse.
"""

from __future__ import annotations

import ast

from hops_tpu.analysis.engine import Context, Rule, dotted_name, register
from hops_tpu.analysis.model import Finding, ParsedFile

#: Path fragments that put a file in scope: the serving tiers.
SCOPE = (
    "hops_tpu/modelrepo/fleet/",
    "hops_tpu/modelrepo/serving.py",
    "hops_tpu/modelrepo/lm_engine.py",
    "hops_tpu/runtime/qos.py",
)


def _bound_arg(node: ast.Call) -> ast.expr | None:
    """The bound expression of a priority-queue constructor call: first
    positional, or the ``maxsize=`` / ``bound=`` keyword."""
    if node.args:
        return node.args[0]
    for kw in node.keywords:
        if kw.arg in ("maxsize", "bound"):
            return kw.value
    return None


def _is_unbounded(arg: ast.expr | None) -> bool:
    if arg is None:
        return True
    if isinstance(arg, ast.Constant):
        v = arg.value
        return v is None or (isinstance(v, (int, float)) and v <= 0)
    return False  # a name/expression: config-supplied, validated at runtime


@register
class UnboundedPriorityQueueRule(Rule):
    name = "unbounded-priority-queue"
    description = (
        "priority queue in the serving tiers constructed without a "
        "hard bound — declare one (qos.BoundedPriorityQueue) so "
        "overload sheds instead of growing the queue to OOM"
    )

    def check_file(self, pf: ParsedFile, ctx: Context) -> list[Finding]:
        if not any(s in pf.relpath for s in SCOPE):
            return []
        findings: list[Finding] = []
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if not name:
                continue
            last = name.split(".")[-1]
            if last not in ("PriorityQueue", "BoundedPriorityQueue"):
                continue
            if _is_unbounded(_bound_arg(node)):
                findings.append(
                    pf.finding(
                        self.name,
                        node,
                        f"{last} constructed without a positive bound — "
                        "serving-tier priority queues must declare a "
                        "bound and shed policy "
                        "(qos.BoundedPriorityQueue(bound, ...))",
                    )
                )
        return findings
