"""Built-in graftlint rules — importing this package registers them.

Each module registers one rule with :func:`hops_tpu.analysis.engine.register`:

- :mod:`.adhoc_http_server` — ``adhoc-http-server``
- :mod:`.jit_purity` — ``jit-purity``
- :mod:`.donation` — ``use-after-donation``
- :mod:`.host_sync` — ``host-sync-in-loop``
- :mod:`.lock_discipline` — ``lock-discipline``
- :mod:`.metric_consistency` — ``metric-name-consistency``
- :mod:`.debug_surfaces` — ``debug-surface-docs``
- :mod:`.hardcoded_loopback` — ``hardcoded-loopback``
- :mod:`.swallowed_exception` — ``swallowed-exception``
- :mod:`.naked_retry` — ``naked-retry-loop``
- :mod:`.json_on_hot_wire` — ``json-on-hot-wire``
- :mod:`.blocking_call` — ``blocking-call-no-deadline``
- :mod:`.relay_json_roundtrip` — ``relay-json-roundtrip``
- :mod:`.unbounded_priority_queue` — ``unbounded-priority-queue``
- :mod:`.lock_order_inversion` — ``lock-order-inversion``
- :mod:`.blocking_under_lock` — ``blocking-under-lock``
- :mod:`.event_loop_stall` — ``event-loop-stall``
- :mod:`.wall_clock_deadline` — ``wall-clock-deadline``
"""

from hops_tpu.analysis.rules import (  # noqa: F401 — registration side effects
    adhoc_http_server,
    blocking_call,
    blocking_under_lock,
    debug_surfaces,
    donation,
    event_loop_stall,
    hardcoded_loopback,
    host_sync,
    jit_purity,
    json_on_hot_wire,
    lock_discipline,
    lock_order_inversion,
    metric_consistency,
    naked_retry,
    relay_json_roundtrip,
    swallowed_exception,
    unbounded_priority_queue,
    wall_clock_deadline,
)
