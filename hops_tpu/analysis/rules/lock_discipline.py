"""lock-discipline: annotated shared state touched without its lock.

The host-side subsystems (loader worker pools, serving driver threads,
the relay lock breaker) guard shared attributes with plain
``threading`` locks — nothing makes a new code path remember. This
rule turns the convention into a checked contract: a trailing

    ``# guarded by: self._lock``

comment on an attribute's defining assignment declares its lock, and
every other access to that attribute in the class must sit lexically
inside ``with self._lock:``. Module-level names annotated the same way
must be accessed under their lock from any function in the file.

Sanctioned exceptions, because they are single-threaded by
construction:

- the defining assignment itself and everything in ``__init__`` (no
  other thread can hold the object yet);
- module-level statements (imports run once, single-threaded);
- functions whose ``def`` line carries the same ``# guarded by:``
  annotation — the documented "caller holds the lock" helper shape
  (e.g. a ``_child()`` only ever called under the registry lock).
"""

from __future__ import annotations

import ast

from hops_tpu.analysis.engine import Context, Rule, register
from hops_tpu.analysis.model import Finding, ParsedFile


def _norm(expr: str) -> str:
    return "".join(expr.split())


def _stmt_covers(node: ast.stmt, line: int) -> bool:
    return node.lineno <= line <= (getattr(node, "end_lineno", node.lineno) or node.lineno)


@register
class LockDisciplineRule(Rule):
    name = "lock-discipline"
    description = (
        "attributes annotated `# guarded by: <lock>` accessed outside a "
        "`with <lock>:` block"
    )

    def check_file(self, pf: ParsedFile, ctx: Context) -> list[Finding]:
        if not pf.guard_comments:
            return []
        parents = pf.parents()

        # -- collect declarations --------------------------------------------
        class_guards: dict[int, dict[str, str]] = {}  # id(ClassDef) -> attr -> lock
        class_nodes: dict[int, ast.ClassDef] = {}
        module_guards: dict[str, str] = {}
        fn_holds: dict[int, set[str]] = {}  # id(FunctionDef) -> held locks
        decl_lines: set[int] = set()

        for line, lock in pf.guard_comments.items():
            lock_n = _norm(lock)
            for node in ast.walk(pf.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if node.lineno == line or (
                        node.body and line < node.body[0].lineno and node.lineno <= line
                    ):
                        fn_holds.setdefault(id(node), set()).add(lock_n)
                if not isinstance(node, (ast.Assign, ast.AnnAssign)) or not _stmt_covers(
                    node, line
                ):
                    continue
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                for t in targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        cls = self._enclosing_class(node, parents)
                        if cls is not None:
                            class_guards.setdefault(id(cls), {})[t.attr] = lock_n
                            class_nodes[id(cls)] = cls
                            decl_lines.add(line)
                    elif isinstance(t, ast.Name) and self._at_module_level(
                        node, parents
                    ):
                        module_guards[t.id] = lock_n
                        decl_lines.add(line)

        findings: list[Finding] = []

        # -- class-attribute guards ------------------------------------------
        # A guard declared on a base class covers its in-file subclasses
        # too (the registry's `_child()` helpers live on subclasses of
        # the `_Metric` that declares `_children`).
        all_classes = [n for n in ast.walk(pf.tree) if isinstance(n, ast.ClassDef)]
        for cls_id, guards in class_guards.items():
            cls = class_nodes[cls_id]
            for scope_cls in self._with_subclasses(cls, all_classes):
                findings.extend(
                    self._check_class(
                        pf, scope_cls, guards, parents, decl_lines, fn_holds
                    )
                )

        # -- module-level guards ---------------------------------------------
        if module_guards:
            for node in ast.walk(pf.tree):
                if not isinstance(node, ast.Name) or node.id not in module_guards:
                    continue
                lock = module_guards[node.id]
                if node.lineno in decl_lines:
                    continue
                if self._at_module_level(node, parents):
                    continue  # import-time init is single-threaded
                if self._held(node, parents, lock, fn_holds, allow_init_of=None):
                    continue
                findings.append(
                    pf.finding(
                        self.name,
                        node,
                        f"`{node.id}` (guarded by `{lock}`) accessed outside "
                        f"`with {lock}:`",
                    )
                )
        return findings

    @staticmethod
    def _with_subclasses(
        cls: ast.ClassDef, all_classes: list[ast.ClassDef]
    ) -> list[ast.ClassDef]:
        """``cls`` plus every in-file class whose base-name chain
        reaches it (name-based, transitive)."""
        out = [cls]
        names = {cls.name}
        changed = True
        while changed:
            changed = False
            for c in all_classes:
                if c in out:
                    continue
                if any(
                    isinstance(b, ast.Name) and b.id in names
                    for b in c.bases
                ):
                    out.append(c)
                    names.add(c.name)
                    changed = True
        return out

    def _check_class(
        self,
        pf: ParsedFile,
        cls: ast.ClassDef,
        guards: dict[str, str],
        parents: dict[int, ast.AST],
        decl_lines: set[int],
        fn_holds: dict[int, set[str]],
    ) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(cls):
            if not isinstance(node, ast.Attribute) or node.attr not in guards:
                continue
            lock = guards[node.attr]
            if node.lineno in decl_lines:
                continue
            if self._held(node, parents, lock, fn_holds, allow_init_of=cls):
                continue
            findings.append(
                pf.finding(
                    self.name,
                    node,
                    f"`{ast.unparse(node)}` (guarded by `{lock}`) accessed "
                    f"outside `with {lock}:`",
                )
            )
        return findings

    @staticmethod
    def _enclosing_class(
        node: ast.AST, parents: dict[int, ast.AST]
    ) -> ast.ClassDef | None:
        cur = parents.get(id(node))
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = parents.get(id(cur))
        return None

    @staticmethod
    def _at_module_level(node: ast.AST, parents: dict[int, ast.AST]) -> bool:
        cur = parents.get(id(node))
        while cur is not None:
            if isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                return False
            cur = parents.get(id(cur))
        return True

    @staticmethod
    def _held(
        node: ast.AST,
        parents: dict[int, ast.AST],
        lock: str,
        fn_holds: dict[int, set[str]],
        allow_init_of: ast.ClassDef | None,
    ) -> bool:
        cur = parents.get(id(node))
        while cur is not None:
            if isinstance(cur, ast.With):
                for item in cur.items:
                    if _norm(ast.unparse(item.context_expr)) == lock:
                        return True
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if lock in fn_holds.get(id(cur), set()):
                    return True
                if (
                    allow_init_of is not None
                    and cur.name == "__init__"
                    and LockDisciplineRule._enclosing_class(cur, parents)
                    is allow_init_of
                ):
                    return True
            cur = parents.get(id(cur))
        return False
