"""adhoc-http-server: all serving rides the shared event-loop core.

The stack spent four PRs converging its five HTTP server sites
(serving replicas, the fleet router, hostd, shardd, the metrics
server) onto ONE selector-based transport
(``runtime/httpserver.HTTPServer``): one IO loop, bounded workers,
pipelining-safe response ordering, keep-alive accounting, slowloris
eviction. A new ``ThreadingHTTPServer`` or ``BaseHTTPRequestHandler``
site would quietly re-grow the thread-per-connection transport the
migration removed — per-connection thread churn, unbounded handler
concurrency, none of the ``hops_tpu_http_*`` observability — and its
behavior under the chaos suites would diverge from every other server
in the process.

Flagged, anywhere in ``hops_tpu/`` EXCEPT ``runtime/httpserver.py``
(the sanctioned core, whose docstring narrates the history):

- instantiating ``ThreadingHTTPServer`` / ``HTTPServer`` /
  ``ThreadingTCPServer`` from ``http.server`` / ``socketserver``
  (dotted spellings included);
- subclassing ``BaseHTTPRequestHandler`` / ``SimpleHTTPRequestHandler``
  (a handler class exists only to feed a stdlib server).

Type annotations and bare imports are NOT flagged —
``telemetry/export.py`` keeps ``handle_metrics_path(handler:
BaseHTTPRequestHandler)`` wrappers for embedders still on the stdlib
transport, and referencing the type is not running a server. Tests and
``bench.py`` are out of scope: the benchmark instantiates the stdlib
transport on purpose, as the *baseline* the event-loop core is measured
against.
"""

from __future__ import annotations

import ast

from hops_tpu.analysis.engine import Context, Rule, dotted_name, register
from hops_tpu.analysis.model import Finding, ParsedFile

#: The one module allowed to speak raw transport (and the only one that
#: may mention the stdlib servers in anger).
SANCTIONED = "hops_tpu/runtime/httpserver.py"

#: Stdlib server classes whose *instantiation* re-grows the
#: thread-per-connection transport.
SERVER_NAMES = frozenset({
    "ThreadingHTTPServer",
    "ThreadingTCPServer",
})

#: Handler base classes whose *subclassing* does the same.
HANDLER_BASES = frozenset({
    "BaseHTTPRequestHandler",
    "SimpleHTTPRequestHandler",
})


def _last(name: str) -> str:
    return name.rsplit(".", 1)[-1]


def _http_server_call(name: str, stdlib_http_names: set[str]) -> bool:
    """Is this call an instantiation of a stdlib server class? Plain
    ``HTTPServer(...)`` is ambiguous with the sanctioned core's own
    class name — it counts only when the file imported it from
    ``http.server``/``socketserver`` (tracked in
    ``stdlib_http_names``) or spells the module out."""
    last = _last(name)
    if last in SERVER_NAMES:
        return True
    if last == "HTTPServer":
        return (name in ("http.server.HTTPServer", "server.HTTPServer")
                or "HTTPServer" in stdlib_http_names and "." not in name)
    return False


@register
class AdhocHTTPServerRule(Rule):
    name = "adhoc-http-server"
    description = (
        "stdlib thread-per-connection HTTP server instantiated or "
        "subclassed outside runtime/httpserver.py — serve through the "
        "shared event-loop core (runtime.httpserver.HTTPServer) instead"
    )

    def check_file(self, pf: ParsedFile, ctx: Context) -> list[Finding]:
        rel = pf.relpath.replace("\\", "/")
        if "hops_tpu/" not in rel or rel.endswith(SANCTIONED):
            return []
        # Names this file imported from the stdlib server modules —
        # disambiguates bare ``HTTPServer(...)`` from the sanctioned
        # core's identically-named class.
        stdlib_http_names: set[str] = set()
        for node in ast.walk(pf.tree):
            if (isinstance(node, ast.ImportFrom)
                    and node.module in ("http.server", "socketserver")):
                stdlib_http_names.update(
                    a.asname or a.name for a in node.names)
        findings: list[Finding] = []
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name and _http_server_call(name, stdlib_http_names):
                    findings.append(pf.finding(
                        self.name, node,
                        f"{_last(name)} instantiated outside the "
                        "sanctioned transport — serve through "
                        "runtime.httpserver.HTTPServer (one event "
                        "loop, bounded workers, hops_tpu_http_* "
                        "metrics)",
                    ))
            elif isinstance(node, ast.ClassDef):
                for base in node.bases:
                    bname = dotted_name(base)
                    if bname and _last(bname) in HANDLER_BASES:
                        findings.append(pf.finding(
                            self.name, node,
                            f"class {node.name} subclasses "
                            f"{_last(bname)} — stdlib handler classes "
                            "exist only to feed the thread-per-"
                            "connection transport; port the routes to "
                            "a runtime.httpserver route function",
                        ))
                        break
        return findings
