"""swallowed-exception: bare ``except:`` and ``except Exception: pass``.

In the runtime/serving paths an exception swallowed without a trace is
how a serving host keeps answering after its state machine corrupted,
or a preemption handler "succeeds" without checkpointing. Two shapes:

- a bare ``except:`` — catches ``SystemExit``/``KeyboardInterrupt``
  too, so even Ctrl-C and supervisor shutdown get eaten;
- ``except Exception:`` (or ``BaseException``) whose body is only
  ``pass`` — the error vanishes without a log line.

A handler that logs, re-raises, or does anything at all with the
``Exception`` case is fine; suppressions on genuinely-intentional
swallows (interpreter teardown, client-went-away) should say why in
the same comment.
"""

from __future__ import annotations

import ast

from hops_tpu.analysis.engine import Context, Rule, dotted_name, register
from hops_tpu.analysis.model import Finding, ParsedFile

_BROAD = {"Exception", "BaseException"}


def _is_pass_only(body: list[ast.stmt]) -> bool:
    return all(
        isinstance(s, ast.Pass)
        or (isinstance(s, ast.Expr) and isinstance(s.value, ast.Constant))
        for s in body
    )


@register
class SwallowedExceptionRule(Rule):
    name = "swallowed-exception"
    description = (
        "bare `except:` anywhere, and `except Exception:`/`BaseException:` "
        "whose body is only `pass`"
    )

    def check_file(self, pf: ParsedFile, ctx: Context) -> list[Finding]:
        findings = []
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                findings.append(
                    pf.finding(
                        self.name,
                        node,
                        "bare `except:` catches SystemExit/KeyboardInterrupt; "
                        "name the exception (and handle or log it)",
                    )
                )
                continue
            types = (
                node.type.elts if isinstance(node.type, ast.Tuple) else [node.type]
            )
            broad = [
                dotted_name(t)
                for t in types
                if dotted_name(t).split(".")[-1] in _BROAD
            ]
            exc = broad[0] if broad else ""
            if broad and _is_pass_only(node.body):
                findings.append(
                    pf.finding(
                        self.name,
                        node,
                        f"`except {exc}: pass` swallows the error without a "
                        "trace; log it, narrow the type, or justify with an "
                        "inline disable",
                    )
                )
        return findings
