"""debug-surface-docs: every debug route and flight-recorder event kind
is documented in docs/operations.md.

Sibling of ``metric-name-consistency``, for the observability surfaces
PR 10 added: operators reach for ``GET /debug/*`` and read flight-
recorder dumps DURING incidents — an undocumented route or event kind
is a surface nobody will find at 3am, and the docs' event catalog is
what post-incident tooling greps against. Two statically-checkable
contracts:

- every string literal starting with ``/debug/`` or ``/admin/``
  (route comparisons, clients, tests alike; f-string fragments count)
  must appear — normalized without its trailing slash — in
  ``docs/operations.md`` (admin routes are operator verbs — drain,
  capture start/stop — an undocumented one is a control plane nobody
  can operate);
- every literal event kind passed to ``<receiver ending in
  flight>.record("<kind>", ...)`` (the :mod:`hops_tpu.runtime.flight`
  convention: ``flight.record(...)`` / ``FLIGHT.record(...)``) must
  appear in the docs' flight-recorder event catalog.

Dynamically-built kinds/routes are out of static reach and skipped,
exactly like dynamically-built metric names.
"""

from __future__ import annotations

import ast
import re

from hops_tpu.analysis.engine import Context, Rule, register
from hops_tpu.analysis.model import Finding, ParsedFile


def _receiver_is_flight(node: ast.AST) -> bool:
    try:
        text = ast.unparse(node)
    except Exception:  # pragma: no cover
        return False
    # The final dotted segment must BE the flight module / recorder
    # (`flight`, `FLIGHT`, an aliased `_flight`, `runtime.flight`) —
    # a suffix match would swallow the pervasive `inflight` trackers.
    return text.split(".")[-1].lstrip("_").lower() == "flight"


def _collect(pf: ParsedFile) -> tuple[list[tuple[ast.AST, str]],
                                      list[tuple[ast.AST, str]]]:
    routes: list[tuple[ast.AST, str]] = []
    kinds: list[tuple[ast.AST, str]] = []
    for node in ast.walk(pf.tree):
        if (isinstance(node, ast.Constant) and isinstance(node.value, str)
                and node.value.startswith(("/debug/", "/admin/"))):
            route = node.value.rstrip("/")
            if route not in ("/debug", "/admin"):  # a bare prefix is not a route
                routes.append((node, route))
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "record"
            and _receiver_is_flight(node.func.value)
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            kinds.append((node, node.args[0].value))
    return routes, kinds


@register
class DebugSurfaceDocsRule(Rule):
    name = "debug-surface-docs"
    description = (
        "every /debug/* and /admin/* route and flight-recorder event "
        "kind is documented in docs/operations.md"
    )

    def check_project(
        self, files: list[ParsedFile], ctx: Context
    ) -> list[Finding]:
        docs = ctx.docs_text()
        if docs is None:
            return []
        findings: list[Finding] = []
        seen_routes: set[str] = set()
        seen_kinds: set[str] = set()
        for pf in files:
            routes, kinds = _collect(pf)
            for node, route in routes:
                if route in seen_routes:
                    continue
                if route not in docs:
                    seen_routes.add(route)
                    findings.append(pf.finding(
                        self.name, node,
                        f"debug route `{route}` is referenced in code but "
                        "missing from docs/operations.md — document it "
                        "(operators discover debug surfaces from that file)",
                    ))
            for node, kind in kinds:
                if kind in seen_kinds:
                    continue
                if not re.search(rf"\b{re.escape(kind)}\b", docs):
                    seen_kinds.add(kind)
                    findings.append(pf.finding(
                        self.name, node,
                        f"flight-recorder event kind `{kind}` is recorded "
                        "in code but missing from docs/operations.md's "
                        "event catalog — document it (incident tooling "
                        "greps dumps against that catalog)",
                    ))
        return findings
