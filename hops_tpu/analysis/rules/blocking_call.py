"""blocking-call-no-deadline: fleet cross-process calls need a budget.

The fleet tier (``hops_tpu/modelrepo/fleet/``) is a control plane made
of cross-process HTTP calls: the router forwards to replicas, the
scraper reads their ``/metrics.json``, the replica manager probes
``/healthz`` and posts ``/admin/drain``. A single such call issued
WITHOUT a deadline wedges its thread on a half-dead peer — and these
threads are exactly the ones capacity decisions ride on (a wedged
scraper freezes the load view; a wedged drain probe freezes a
rollout). The kernel's default TCP timeouts are minutes; the fleet's
decision cadence is milliseconds.

Flagged, in fleet-scoped files only: calls to the known blocking
network primitives — ``urllib.request.urlopen`` (and any ``*.urlopen``
/ bare ``urlopen``), ``socket.create_connection``, and the
``requests`` verbs — that neither pass an explicit ``timeout``
argument nor sit lexically inside a ``resilience.with_deadline(...)``
call. The fix is the one the rest of the module already uses: thread a
``timeout=`` through (most of the fleet derives it from
``forward_timeout_s`` / ``scrape_interval_s``), or wrap the call in
``with_deadline`` when the budget spans more than the one syscall.
"""

from __future__ import annotations

import ast

from hops_tpu.analysis.engine import Context, Rule, dotted_name, register
from hops_tpu.analysis.model import Finding, ParsedFile

#: Path fragment that puts a file in scope: the fleet control plane.
SCOPE = "hops_tpu/modelrepo/fleet/"

#: Dotted names (suffix-matched on the last segment for attribute
#: forms) of blocking network calls that accept a ``timeout``.
_BLOCKING_LAST = {"urlopen", "create_connection"}
_REQUESTS_VERBS = {"get", "post", "put", "delete", "head", "patch", "request"}


def _is_blocking_call(node: ast.Call) -> bool:
    name = dotted_name(node.func)
    if not name:
        return False
    last = name.split(".")[-1]
    if last in _BLOCKING_LAST:
        return True
    # requests.get(...) etc. — only the requests module's verbs; a bare
    # get() is dict/queue idiom, not a network call.
    return (
        last in _REQUESTS_VERBS
        and name.split(".")[0] == "requests"
    )


def _has_timeout(node: ast.Call) -> bool:
    if any(kw.arg == "timeout" for kw in node.keywords):
        return True
    name = dotted_name(node.func) or ""
    last = name.split(".")[-1]
    # socket.create_connection((host, port), timeout) — positional form.
    if last == "create_connection" and len(node.args) >= 2:
        return True
    # urlopen(url, data, timeout) — timeout is the third positional.
    if last == "urlopen" and len(node.args) >= 3:
        return True
    return False


def _deadline_wrapped(node: ast.Call, parents: dict[int, ast.AST]) -> bool:
    """Is this call a lexical descendant of a ``with_deadline(...)``
    call (e.g. ``with_deadline(lambda: urlopen(u), 2.0)``)? That budget
    covers the blocking call, so no finding."""
    cur = parents.get(id(node))
    while cur is not None:
        if isinstance(cur, ast.Call):
            name = dotted_name(cur.func) or ""
            if name.split(".")[-1] == "with_deadline":
                return True
        cur = parents.get(id(cur))
    return False


@register
class BlockingCallNoDeadlineRule(Rule):
    name = "blocking-call-no-deadline"
    description = (
        "fleet cross-process HTTP/socket call without an explicit "
        "timeout or with_deadline wrapper — a half-dead peer wedges "
        "the router/autoscaler/rollout thread"
    )

    def check_file(self, pf: ParsedFile, ctx: Context) -> list[Finding]:
        if SCOPE not in pf.relpath:
            return []
        parents = pf.parents()
        findings = []
        for node in ast.walk(pf.tree):
            if not (isinstance(node, ast.Call) and _is_blocking_call(node)):
                continue
            if _has_timeout(node) or _deadline_wrapped(node, parents):
                continue
            callee = dotted_name(node.func) or "<call>"
            findings.append(
                pf.finding(
                    self.name,
                    node,
                    f"blocking call {callee}(...) in fleet code has no "
                    "deadline — pass timeout= or wrap in "
                    "resilience.with_deadline (a wedged peer must cost "
                    "a bounded wait, not a frozen control plane)",
                )
            )
        return findings
