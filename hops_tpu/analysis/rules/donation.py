"""use-after-donation: reading a buffer after handing it to XLA.

``donate_argnums`` (and ``Strategy.step``'s default
``donate_state=True``) tells XLA it may overwrite the argument's buffer
in place — the standard trick that halves train-state memory. The
Python name still points at the donated array, and touching it again
raises at best (``Array has been deleted``) and at worst silently reads
repurposed memory on backends that don't track deletion. The correct
pattern rebinds in the same statement (``state, m = step(state, b)``);
everything else is a latent crash that only fires on a real device,
never under pytest on CPU.

Detected shapes, per function (or module) scope:

- ``g = jax.jit(f, donate_argnums=(0,)); g(x); ... x ...`` — ``x``
  read after the donating call without an intervening rebind;
- the same with the jitted callable invoked inline;
- ``step = strategy.step(fn)`` (donation on by default) called inside
  a ``for``/``while`` loop without rebinding the donated argument —
  iteration 2 passes a dead buffer.
"""

from __future__ import annotations

import ast
from typing import Iterator

from hops_tpu.analysis.engine import Context, Rule, call_name, register
from hops_tpu.analysis.model import Finding, ParsedFile


def _pos(node: ast.AST) -> tuple[int, int]:
    return (node.lineno, node.col_offset)


def _end_pos(node: ast.AST) -> tuple[int, int]:
    return (
        getattr(node, "end_lineno", node.lineno),
        getattr(node, "end_col_offset", node.col_offset),
    )


def _donated_positions(call: ast.Call) -> tuple[int, ...] | None:
    """Donated argument indices if ``call`` builds a donating callable."""
    name = call_name(call.func)
    if name in ("jit", "pjit"):
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                v = kw.value
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    return (v.value,)
                if isinstance(v, (ast.Tuple, ast.List)):
                    out = []
                    for e in v.elts:
                        if isinstance(e, ast.Constant) and isinstance(e.value, int):
                            out.append(e.value)
                        else:
                            return None
                    return tuple(out)
                return None
        return None
    if (
        isinstance(call.func, ast.Attribute)
        and name == "step"
        and call.args
        and isinstance(call.args[0], ast.Name)
    ):
        # Strategy.step(fn): donate_state defaults to True.
        for kw in call.keywords:
            if kw.arg == "donate_state" and isinstance(kw.value, ast.Constant):
                if not kw.value.value:
                    return None
        return (0,)
    return None


def _scope_walk(scope: ast.AST) -> Iterator[ast.AST]:
    """Walk a scope without descending into nested function/class defs."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        if hasattr(node, "lineno"):
            yield node
        if not isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
        ):
            stack.extend(ast.iter_child_nodes(node))


@register
class UseAfterDonationRule(Rule):
    name = "use-after-donation"
    description = (
        "an argument read after being passed through donate_argnums/"
        "donate_state — the buffer belongs to XLA now"
    )

    def check_file(self, pf: ParsedFile, ctx: Context) -> list[Finding]:
        findings: list[Finding] = []
        scopes: list[ast.AST] = [pf.tree] + [
            n
            for n in ast.walk(pf.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            findings.extend(self._check_scope(pf, scope))
        return findings

    def _check_scope(self, pf: ParsedFile, scope: ast.AST) -> list[Finding]:
        nodes = list(_scope_walk(scope))
        # The whole-file map works scope-bounded too: every ancestor
        # walk below terminates at `scope` explicitly.
        parents = pf.parents()

        donors: dict[str, tuple[int, ...]] = {}
        donation_calls: list[tuple[ast.Call, tuple[int, ...]]] = []
        for n in sorted(
            (x for x in nodes if isinstance(x, (ast.Assign, ast.Call))),
            key=_pos,
        ):
            if isinstance(n, ast.Assign):
                donated = (
                    _donated_positions(n.value)
                    if isinstance(n.value, ast.Call)
                    else None  # rebound to a non-call: no longer a donor
                )
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        if donated is not None:
                            donors[t.id] = donated
                        else:
                            donors.pop(t.id, None)
            elif isinstance(n, ast.Call):
                if isinstance(n.func, ast.Name) and n.func.id in donors:
                    donation_calls.append((n, donors[n.func.id]))
                elif isinstance(n.func, ast.Call):
                    donated = _donated_positions(n.func)
                    if donated is not None:
                        donation_calls.append((n, donated))

        findings: list[Finding] = []
        for call, positions in donation_calls:
            for i in positions:
                if i < len(call.args) and isinstance(call.args[i], ast.Name):
                    findings.extend(
                        self._check_donated_name(
                            pf, scope, nodes, parents, call, call.args[i].id
                        )
                    )
        return findings

    def _check_donated_name(
        self,
        pf: ParsedFile,
        scope: ast.AST,
        nodes: list[ast.AST],
        parents: dict[int, ast.AST],
        call: ast.Call,
        var: str,
    ) -> list[Finding]:
        # Does the statement holding the call rebind the name (the
        # sanctioned `state, m = step(state, b)` shape)?
        anc = parents.get(id(call))
        rebinding_stmt = False
        loop: ast.For | ast.While | None = None
        while anc is not None and anc is not scope:
            if isinstance(anc, ast.Assign) and any(
                isinstance(t2, ast.Name) and t2.id == var or var in _store_names(t2)
                for t2 in anc.targets
            ):
                rebinding_stmt = True
            if isinstance(anc, (ast.For, ast.While)) and loop is None:
                loop = anc
            anc = parents.get(id(anc))

        if loop is not None:
            stored_in_loop = any(
                isinstance(n, ast.Name)
                and n.id == var
                and isinstance(n.ctx, ast.Store)
                for n in ast.walk(loop)
            )
            if not stored_in_loop:
                return [
                    pf.finding(
                        self.name,
                        call,
                        f"`{var}` is donated by `{ast.unparse(call.func)}` "
                        "inside a loop but never rebound there — iteration 2 "
                        "passes a deleted buffer; rebind it "
                        f"(`{var}, ... = ...`)",
                    )
                ]
            return []  # rebound somewhere in the loop: stream-carried

        if rebinding_stmt:
            return []
        end = _end_pos(call)
        later_stores = sorted(
            (
                _pos(n)
                for n in nodes
                if isinstance(n, ast.Name)
                and n.id == var
                and isinstance(n.ctx, ast.Store)
                and _pos(n) > end
            ),
        )
        horizon = later_stores[0] if later_stores else (1 << 30, 0)
        out = []
        for n in sorted(nodes, key=_pos):
            if (
                isinstance(n, ast.Name)
                and n.id == var
                and isinstance(n.ctx, ast.Load)
                and end < _pos(n) < horizon
            ):
                out.append(
                    pf.finding(
                        self.name,
                        n,
                        f"`{var}` read after being donated to "
                        f"`{ast.unparse(call.func)}` — the buffer belongs "
                        "to XLA; use the call's result instead",
                    )
                )
        return out


def _store_names(target: ast.AST) -> set[str]:
    return {
        n.id
        for n in ast.walk(target)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)
    }
