"""json-on-hot-wire: JSON codec calls on request/response bodies in the
packed-wire tier.

The packed columnar codec (``runtime/wirecodec.py``) is the negotiated
wire format for tensor-shaped bodies on the serving and feature data
planes; ``bench.py --hot-path`` prices its decode at multiples of
``json.loads`` on the same body. This rule keeps JSON from creeping
back onto those hot paths: inside the three wire-tier files, any
``json.loads`` of a request/response body variable and any
``json.dumps(...).encode()`` body serialization is flagged.

Negotiation keeps JSON as the *default* format on purpose, so the
legitimate sites — the negotiated JSON branch, error/debug responses,
control-plane parses — stay, each carrying a
``# graftlint: disable=json-on-hot-wire`` comment whose justification
names WHY that site is exempt. A new un-annotated site is a finding:
either it belongs on the packed path, or it needs to argue its case in
a disable comment.
"""

from __future__ import annotations

import ast

from hops_tpu.analysis.engine import Context, Rule, dotted_name, register
from hops_tpu.analysis.model import Finding, ParsedFile

#: The wire-tier files in scope — the layers a request/response body
#: traverses between client and predictor/shard.
SCOPES = (
    "hops_tpu/modelrepo/serving.py",
    "hops_tpu/modelrepo/fleet/router.py",
    "hops_tpu/featurestore/online_serving.py",
)

#: Variable names that hold raw request/response bodies in the scoped
#: files (the HTTP route/exchange contracts).
BODY_NAMES = frozenset({"body", "raw_body", "body_in", "raw", "data"})


def _is_json_call(node: ast.AST, fn: str) -> bool:
    name = dotted_name(node.func) if isinstance(node, ast.Call) else None
    return (name or "").split(".")[0] == "json" \
        and (name or "").split(".")[-1] == fn


def _names_a_body(expr: ast.AST) -> bool:
    """Does ``expr`` reference a body variable? Catches the bare Name
    and the ``body or b"{}"`` default idiom."""
    if isinstance(expr, ast.Name):
        return expr.id in BODY_NAMES
    if isinstance(expr, ast.BoolOp):
        return any(_names_a_body(v) for v in expr.values)
    return False


@register
class JsonOnHotWireRule(Rule):
    name = "json-on-hot-wire"
    description = (
        "json.loads of a request/response body (or json.dumps(...)"
        ".encode() body serialization) inside the packed-wire serving/"
        "feature tier — use runtime/wirecodec.py, or justify the JSON "
        "fallback in a disable comment"
    )

    def check_file(self, pf: ParsedFile, ctx: Context) -> list[Finding]:
        if not any(pf.relpath.endswith(scope) for scope in SCOPES):
            return []
        findings: list[Finding] = []
        for node in ast.walk(pf.tree):
            if (_is_json_call(node, "loads") and node.args
                    and _names_a_body(node.args[0])):
                findings.append(pf.finding(
                    self.name, node,
                    "json.loads of a wire body on the packed-codec tier "
                    "— decode via runtime/wirecodec.py, or justify the "
                    "JSON path in a disable comment",
                ))
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "encode"
                and _is_json_call(node.func.value, "dumps")
            ):
                findings.append(pf.finding(
                    self.name, node,
                    "json.dumps(...).encode() body serialization on the "
                    "packed-codec tier — encode via runtime/wirecodec.py, "
                    "or justify the JSON path in a disable comment",
                ))
        return findings
