"""wall-clock-deadline: deadlines and elapsed checks must not use time.time().

``time.time()`` is the WALL clock: NTP slews it, ntpdate and operators
step it, leap smearing bends it. Any deadline minted from it — or any
elapsed/timeout comparison computed with it — inherits those jumps: a
backward step stretches a 5 s drain wait into minutes, a forward step
expires every lease and poll loop in the process at once. The platform
learned this the hard way in the placement tier (a host-clock step aged
out perfectly healthy hosts), which is why registry aging and the hostd
lease run on receiver-side ``time.monotonic()`` arrival time. This rule
keeps the rest of the tree honest.

Flagged, everywhere in the tree:

- **deadline mints**: an assignment whose value is an ``Add`` expression
  containing a ``time.time()`` call — ``deadline = time.time() + ttl``
  is a future instant on a clock that can move underneath it;
- **wall-clock comparisons**: any comparison with a ``time.time()``
  call in an operand — ``while time.time() < deadline`` and
  ``if time.time() - t0 > budget`` both measure duration on the wall
  clock.

NOT flagged: bare timestamp captures (``ts = time.time()`` — event
times and display stamps are exactly what the wall clock is for),
``Sub`` durations outside comparisons (``duration_s = time.time() -
start`` in a result record is display, not control flow), and
``time.time()`` buried in another call's argument list (comparing
``f(time.time())``'s result compares what ``f`` computes). Comparisons
against file mtimes are wall-vs-wall and legitimate — suppress those
with ``# graftlint: disable=wall-clock-deadline`` on the line.

The fix is mechanical: mint and compare with ``time.monotonic()``; keep
``time.time()`` only for values that leave the process (announce
stamps, event times, log records).
"""

from __future__ import annotations

import ast

from hops_tpu.analysis.engine import Context, Rule, dotted_name, register
from hops_tpu.analysis.model import Finding, ParsedFile


def _has_wall_clock_call(node: ast.AST) -> bool:
    """Does this subtree contain a ``time.time()`` call whose VALUE
    reaches the enclosing operator? Argument lists of other calls are
    opaque: comparing ``f(time.time())``'s result is comparing whatever
    ``f`` computes, not the clock."""
    if isinstance(node, ast.Call):
        return dotted_name(node.func) == "time.time"
    return any(_has_wall_clock_call(c) for c in ast.iter_child_nodes(node))


def _is_add_mint(value: ast.AST) -> bool:
    """Is ``value`` an Add expression with ``time.time()`` inside —
    i.e. a future-instant deadline minted on the wall clock?"""
    for sub in ast.walk(value):
        if (isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Add)
                and (_has_wall_clock_call(sub.left)
                     or _has_wall_clock_call(sub.right))):
            return True
    return False


@register
class WallClockDeadlineRule(Rule):
    name = "wall-clock-deadline"
    description = (
        "deadline or elapsed-time check computed with time.time() — an "
        "NTP step or slew moves the deadline; use time.monotonic()"
    )

    def check_file(self, pf: ParsedFile, ctx: Context) -> list[Finding]:
        findings = []
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Compare):
                operands = [node.left, *node.comparators]
                if any(_has_wall_clock_call(op) for op in operands):
                    findings.append(pf.finding(
                        self.name, node,
                        "comparison measures time with time.time() — a "
                        "clock step breaks the wait/expiry; compare "
                        "time.monotonic() instants instead",
                    ))
            elif isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = node.value
                if value is not None and _is_add_mint(value):
                    findings.append(pf.finding(
                        self.name, node,
                        "deadline minted as time.time() + budget — the "
                        "wall clock can jump past (or away from) it; "
                        "mint deadlines from time.monotonic()",
                    ))
        return findings
