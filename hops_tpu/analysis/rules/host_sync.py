"""host-sync-in-loop: device->host synchronization inside step loops.

JAX dispatch is asynchronous: the Python loop runs ahead of the TPU,
which is what keeps the device busy. ``.item()``, ``float(...)``,
``np.asarray(...)``, ``jax.device_get(...)`` and ``block_until_ready``
on a device value force the host to wait for the step to finish — one
per iteration turns the pipeline into lock-step and shows up as idle
accelerator (the exact stall the parallel input pipeline exists to
avoid). Pull values out every N steps, or log asynchronously.

A loop is a *step loop* when its body calls something step-shaped
(``step``, ``train_step``, ``step_fn``, ``stepped``...). Device values
are names bound from those calls (tuple-unpack aware) plus any
subscript/attribute path rooted at them. ``block_until_ready`` /
``jax.device_get`` are flagged on any argument inside a step loop —
their only purpose is synchronization.
"""

from __future__ import annotations

import ast
import re

from hops_tpu.analysis.engine import (
    Context,
    Rule,
    assigned_names,
    call_name,
    dotted_name,
    register,
    root_name,
)
from hops_tpu.analysis.model import Finding, ParsedFile

_STEP_NAME_RE = re.compile(r"(^|_)step(_|$)|^stepped$")


def _is_step_call(node: ast.Call) -> bool:
    return bool(_STEP_NAME_RE.search(call_name(node.func)))


@register
class HostSyncInLoopRule(Rule):
    name = "host-sync-in-loop"
    description = (
        ".item()/float()/np.asarray/jax.device_get/block_until_ready on "
        "device values inside for/while step loops"
    )

    def check_file(self, pf: ParsedFile, ctx: Context) -> list[Finding]:
        findings: list[Finding] = []
        # Outermost step loops only: a nested loop's findings would
        # duplicate under both.
        claimed: set[int] = set()
        for node in ast.walk(pf.tree):
            if isinstance(node, (ast.For, ast.While)) and id(node) not in claimed:
                body = list(ast.walk(node))
                step_calls = [
                    n for n in body if isinstance(n, ast.Call) and _is_step_call(n)
                ]
                if not step_calls:
                    continue
                for inner in body:
                    if isinstance(inner, (ast.For, ast.While)) and inner is not node:
                        claimed.add(id(inner))
                findings.extend(self._check_loop(pf, node, step_calls))
        return findings

    def _check_loop(
        self, pf: ParsedFile, loop: ast.For | ast.While, step_calls: list[ast.Call]
    ) -> list[Finding]:
        step_ids = {id(c) for c in step_calls}
        device_names: set[str] = set()
        for n in ast.walk(loop):
            if isinstance(n, ast.Assign) and id(n.value) in step_ids:
                for t in n.targets:
                    device_names |= assigned_names(t)
            elif isinstance(n, ast.AnnAssign) and n.value is not None and id(n.value) in step_ids:
                device_names |= assigned_names(n.target)

        def is_device_value(expr: ast.AST) -> bool:
            base = root_name(expr)
            if isinstance(base, ast.Name) and base.id in device_names:
                return True
            return isinstance(base, ast.Call) and _is_step_call(base)

        findings: list[Finding] = []
        for n in ast.walk(loop):
            if not isinstance(n, ast.Call):
                continue
            fname = call_name(n.func)
            dn = dotted_name(n.func)
            if fname == "block_until_ready":
                what = (
                    f"`{ast.unparse(n)}`"
                    if len(ast.unparse(n)) < 60
                    else "`block_until_ready`"
                )
                findings.append(
                    pf.finding(
                        self.name,
                        n,
                        f"{what} inside a step loop stalls dispatch every "
                        "iteration; sync once after the loop",
                    )
                )
            elif dn in ("jax.device_get", "device_get"):
                findings.append(
                    pf.finding(
                        self.name,
                        n,
                        f"`{dn}` inside a step loop forces a device->host "
                        "sync every iteration; fetch every N steps or "
                        "after the loop",
                    )
                )
            elif fname == "item" and isinstance(n.func, ast.Attribute):
                if is_device_value(n.func.value):
                    findings.append(
                        pf.finding(
                            self.name,
                            n,
                            f"`{ast.unparse(n.func.value)}.item()` on a step "
                            "result blocks on the device every iteration",
                        )
                    )
            elif (
                isinstance(n.func, ast.Name)
                and n.func.id in ("float", "int")
                and n.args
                and is_device_value(n.args[0])
            ):
                findings.append(
                    pf.finding(
                        self.name,
                        n,
                        f"`{n.func.id}({ast.unparse(n.args[0])})` on a step "
                        "result blocks on the device every iteration",
                    )
                )
            elif (
                dn in ("np.asarray", "numpy.asarray")  # jnp.asarray stays on device
                and n.args
                and is_device_value(n.args[0])
            ):
                findings.append(
                    pf.finding(
                        self.name,
                        n,
                        f"`{dn}({ast.unparse(n.args[0])})` copies a step "
                        "result to host every iteration",
                    )
                )
        return findings
