"""relay-json-roundtrip: parse→re-serialize churn on relay paths.

The fleet router's forward path used to ``json.loads`` every replica
response just to ``json.dumps`` it straight back to the client — a full
parse + re-serialize per hop (~0.4 ms/request on the bench body,
``bench.py --hot-path``) that changes nothing but byte order of dict
keys. The zero-copy relay removed it; this rule keeps it removed.

Flagged, in fleet/serving code only:

- a variable assigned from ``json.loads(...)`` whose ONLY uses are as
  the serialized argument of ``json.dumps(...)`` — the object was never
  inspected, so the bytes should have been relayed as-is;
- the direct nesting ``json.dumps(json.loads(...))``.

Parsing that actually reads the object (``payload["key"]``, mutation,
a conditional) is the legitimate lazy-parse path and is not flagged.
"""

from __future__ import annotations

import ast

from hops_tpu.analysis.engine import Context, Rule, dotted_name, register
from hops_tpu.analysis.model import Finding, ParsedFile

#: Path fragments that put a file in scope: the serving relay tier.
SCOPES = ("hops_tpu/modelrepo/fleet/", "hops_tpu/modelrepo/serving.py")


def _is_json_call(node: ast.AST, fn: str) -> bool:
    return (
        isinstance(node, ast.Call)
        and (dotted_name(node.func) or "").split(".")[-1] == fn
        and (dotted_name(node.func) or "").split(".")[0] == "json"
    )


def _dumps_arg_ids(func_node: ast.AST) -> set[int]:
    """ids of every expression node that is the first argument of a
    ``json.dumps(...)`` call inside ``func_node``."""
    out: set[int] = set()
    for node in ast.walk(func_node):
        if _is_json_call(node, "dumps") and node.args:
            out.add(id(node.args[0]))
    return out


@register
class RelayJsonRoundtripRule(Rule):
    name = "relay-json-roundtrip"
    description = (
        "json.loads(...) whose result is only re-json.dumps'ed "
        "unmodified on a fleet/serving relay path — relay the bytes "
        "instead of paying a parse + re-serialize per hop"
    )

    def check_file(self, pf: ParsedFile, ctx: Context) -> list[Finding]:
        if not any(scope in pf.relpath for scope in SCOPES):
            return []
        findings: list[Finding] = []
        for func in ast.walk(pf.tree):
            if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            dumps_args = _dumps_arg_ids(func)
            # Direct nesting: json.dumps(json.loads(x)).
            for node in ast.walk(func):
                if (
                    _is_json_call(node, "loads")
                    and id(node) in dumps_args
                ):
                    findings.append(pf.finding(
                        self.name, node,
                        "json.dumps(json.loads(...)) on a relay path — "
                        "the parsed object is never read; pass the "
                        "bytes through",
                    ))
            # Variable form: x = json.loads(...); every later use of x
            # is json.dumps(x).
            for target, assign in _loads_assignments(func):
                uses = [
                    n for n in ast.walk(func)
                    if isinstance(n, ast.Name)
                    and n.id == target
                    and isinstance(n.ctx, ast.Load)
                ]
                if uses and all(id(u) in dumps_args for u in uses):
                    findings.append(pf.finding(
                        self.name, assign,
                        f"{target!r} is parsed with json.loads but only "
                        "ever re-json.dumps'ed unmodified — relay the "
                        "original bytes instead",
                    ))
        return findings


def _loads_assignments(func: ast.AST):
    """(name, assign-node) for simple ``x = json.loads(...)`` bindings
    directly inside ``func`` (any nesting depth, single Name target)."""
    for node in ast.walk(func):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and _is_json_call(node.value, "loads")
        ):
            yield node.targets[0].id, node
