"""lock-order-inversion: cycles in the global lock-acquisition graph.

Two code paths that take the same pair of locks in opposite order can
deadlock the moment both run concurrently — the classic inversion, and
invisible to any single-file analysis when (as in the serving stack)
the two acquisitions live in different modules joined by a method call.
The whole-program model (:mod:`hops_tpu.analysis.concurrency`) builds
an edge A→B whenever lock B is acquired — lexically or through a
resolved call — while A is held; any cycle is reported once, with both
acquisition chains spelled out file:line by file:line in the finding
detail.

Fix by picking one order and sticking to it (usually: release the
narrow lock before calling into the other subsystem). Locks here are
``threading`` primitives with stable identities (``file:Class.attr`` /
``file:name``); re-entry of the same lock is out of scope (RLock by
design, and a plain-Lock self-deadlock is a different defect).
"""

from __future__ import annotations

from hops_tpu.analysis import concurrency
from hops_tpu.analysis.engine import Context, Rule, register
from hops_tpu.analysis.model import Finding, ParsedFile


@register
class LockOrderInversionRule(Rule):
    name = "lock-order-inversion"
    description = (
        "two code paths acquire the same pair of locks in opposite order "
        "(cycle in the whole-program lock graph)"
    )

    def check_project(
        self, files: list[ParsedFile], ctx: Context
    ) -> list[Finding]:
        model = concurrency.get_model(files, ctx)
        by_path = {pf.relpath: pf for pf in files}
        findings: list[Finding] = []
        for inv in model.inversions():
            path, line, _ = inv.chain_ab[-1]
            pf = by_path.get(path)
            if pf is None:
                continue
            detail = "acquisition order %s -> %s:\n%s\nconflicting order %s -> %s:\n%s" % (
                inv.a, inv.b, concurrency._fmt_chain(inv.chain_ab),
                inv.b, inv.a, concurrency._fmt_chain(inv.chain_ba),
            )
            findings.append(
                Finding(
                    rule=self.name,
                    path=path,
                    line=line,
                    col=0,
                    message=(
                        f"lock-order inversion: `{inv.a}` then `{inv.b}` in "
                        f"`{inv.func_ab}` conflicts with `{inv.b}` then "
                        f"`{inv.a}` in `{inv.func_ba}`"
                    ),
                    symbol=pf.symbol_at(line),
                    detail=detail,
                    related=tuple(sorted(
                        {p for p, _, _ in inv.chain_ab + inv.chain_ba}
                        - {path}
                    )),
                )
            )
        return findings
