"""metric-name-consistency: the registry, the docs, and every module
agree on what each ``hops_tpu_*`` metric is.

The telemetry registry raises on conflicting re-declarations — but only
when both declarers actually run in one process, which CI never
arranges (the serving host and a training job each import half the
tree). And nothing at all checks docs/operations.md, whose metric
tables are the operator contract dashboards are built on. This
project-level rule closes both gaps statically:

- every literal ``hops_tpu_*`` name registered via
  ``*.counter/gauge/histogram(...)`` must appear in
  ``docs/operations.md`` (resolved module-level string constants count
  as literals);
- a name must have exactly ONE metric type across all modules;
- two *explicit* bucket declarations for one histogram must be
  identical (omitted/``None`` buckets are a read-back and match
  anything — the registry's own convention).

Dynamically-built names (span histograms) are out of static reach and
skipped.
"""

from __future__ import annotations

import ast
import dataclasses
import re

from hops_tpu.analysis.engine import Context, Rule, register
from hops_tpu.analysis.model import Finding, ParsedFile

_METRIC_METHODS = {"counter", "gauge", "histogram"}
_PREFIX = "hops_tpu_"


@dataclasses.dataclass
class _Registration:
    pf: ParsedFile
    node: ast.Call
    name: str
    type: str
    buckets: str | None  # unparsed expression, None when omitted/None


def _receiver_is_registry(node: ast.AST) -> bool:
    try:
        text = ast.unparse(node)
    except Exception:  # pragma: no cover
        return False
    return text.lower().endswith("registry") or text.lower().endswith("registry_")


def _module_str_constants(tree: ast.Module) -> dict[str, str]:
    out: dict[str, str] = {}
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            out[node.targets[0].id] = node.value.value
    return out


def _collect(pf: ParsedFile) -> list[_Registration]:
    consts = _module_str_constants(pf.tree)
    regs: list[_Registration] = []
    for node in ast.walk(pf.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _METRIC_METHODS
            and _receiver_is_registry(node.func.value)
            and node.args
        ):
            continue
        arg = node.args[0]
        name: str | None = None
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            name = arg.value
        elif isinstance(arg, ast.Name):
            name = consts.get(arg.id)
        if name is None or not name.startswith(_PREFIX):
            continue
        buckets: str | None = None
        for kw in node.keywords:
            if kw.arg == "buckets" and not (
                isinstance(kw.value, ast.Constant) and kw.value.value is None
            ):
                buckets = ast.unparse(kw.value)
        regs.append(_Registration(pf, node, name, node.func.attr, buckets))
    return regs


@register
class MetricNameConsistencyRule(Rule):
    name = "metric-name-consistency"
    description = (
        "every registered hops_tpu_* metric is documented in "
        "docs/operations.md and has one type/bucket declaration tree-wide"
    )

    def check_project(
        self, files: list[ParsedFile], ctx: Context
    ) -> list[Finding]:
        regs: list[_Registration] = []
        for pf in files:
            regs.extend(_collect(pf))
        findings: list[Finding] = []

        by_name: dict[str, list[_Registration]] = {}
        for r in regs:
            by_name.setdefault(r.name, []).append(r)

        docs = ctx.docs_text()
        for metric, sites in sorted(by_name.items()):
            canonical = sites[0]
            for r in sites[1:]:
                if r.type != canonical.type:
                    findings.append(
                        r.pf.finding(
                            self.name,
                            r.node,
                            f"metric `{metric}` registered as {r.type} here "
                            f"but as {canonical.type} in "
                            f"{canonical.pf.relpath} — one name, one type",
                        )
                    )
            explicit = [r for r in sites if r.buckets is not None]
            for r in explicit[1:]:
                if r.buckets != explicit[0].buckets:
                    findings.append(
                        r.pf.finding(
                            self.name,
                            r.node,
                            f"histogram `{metric}` declared with buckets "
                            f"`{r.buckets}` here but `{explicit[0].buckets}` "
                            f"in {explicit[0].pf.relpath} — quantiles would "
                            "disagree across modules",
                        )
                    )
            # Whole-word match: `hops_tpu_feed` must NOT count as
            # documented just because `hops_tpu_feed_batches_total` is.
            if docs is not None and not re.search(
                rf"\b{re.escape(metric)}\b", docs
            ):
                findings.append(
                    canonical.pf.finding(
                        self.name,
                        canonical.node,
                        f"metric `{metric}` is registered in code but "
                        "missing from docs/operations.md — document it "
                        "(operators dashboard off that file)",
                    )
                )
        return findings
