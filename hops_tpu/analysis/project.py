"""Project-wide symbol table and conservative call graph for graftlint.

The per-file rules (PR 4) see one AST at a time; the concurrency rules
need to know that ``self._pool.request(...)`` in ``router.py`` lands in
``httpclient.HTTPPool.request`` and that ``self._lock`` there is a
different lock than the router's. This module builds that view once per
lint run:

- a :class:`ModuleInfo` per parsed file (imports, classes, functions,
  module-level lock variables);
- a :class:`ClassInfo` per class with its methods, resolved in-project
  bases, inferred attribute types (``self.x = ClassName(...)``,
  annotated assignments/parameters), and lock-typed attributes
  (``threading.Lock/RLock/Condition/Semaphore`` constructions plus
  attributes named by a ``# guarded by:`` annotation);
- call resolution: ``self.m()``, ``self.attr.m()``, ``mod.f()``,
  ``ClassName(...)`` and typed-local ``x.m()`` are resolved to project
  :class:`FuncInfo` targets.

Everything is deliberately an UNDER-approximation: an unresolvable call
contributes no edge. The concurrency layer (:mod:`.concurrency`) builds
its lock graph on top, so a missed edge can only hide a finding, never
invent one — the property a zero-findings CI gate needs.

Types are either a :class:`ClassInfo` (project class) or a string tag
for the small set of stdlib types the concurrency rules care about
(``"lock"``, ``"cond"``, ``"event"``, ``"thread"``, ``"selector"``,
``"popen"``, ...). Stdlib-only, like the rest of the analysis package.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator, Union

from hops_tpu.analysis.engine import dotted_name
from hops_tpu.analysis.model import ParsedFile

#: Stdlib constructors / annotations the concurrency layer distinguishes.
BUILTIN_TAGS: dict[str, str] = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "cond",
    "threading.Semaphore": "sem",
    "threading.BoundedSemaphore": "sem",
    "threading.Event": "event",
    "threading.Thread": "thread",
    "subprocess.Popen": "popen",
    "selectors.DefaultSelector": "selector",
    "selectors.BaseSelector": "selector",
    "concurrent.futures.ThreadPoolExecutor": "executor",
    "futures.ThreadPoolExecutor": "executor",
    "queue.Queue": "queue",
    "queue.SimpleQueue": "queue",
}

#: Type tags that make an attribute/variable a lock for graph purposes.
LOCK_TAGS = {"lock", "rlock", "cond", "sem"}

TypeRef = Union["ClassInfo", str]

_AMBIGUOUS = "<ambiguous>"


@dataclasses.dataclass
class FuncInfo:
    """One function or method definition."""

    name: str
    qualname: str  # e.g. ``HTTPPool.request`` or ``with_deadline``
    module: "ModuleInfo"
    node: ast.FunctionDef | ast.AsyncFunctionDef
    cls: "ClassInfo | None" = None

    @property
    def key(self) -> str:
        return f"{self.module.relpath}:{self.qualname}"

    def __hash__(self) -> int:  # identity — one node, one FuncInfo
        return id(self)

    def __eq__(self, other: object) -> bool:
        return self is other


@dataclasses.dataclass
class ClassInfo:
    """One class definition with resolved project bases."""

    name: str
    module: "ModuleInfo"
    node: ast.ClassDef
    methods: dict[str, FuncInfo] = dataclasses.field(default_factory=dict)
    bases: list["ClassInfo"] = dataclasses.field(default_factory=list)
    #: attr name -> inferred type (project class or builtin tag).
    attr_types: dict[str, TypeRef] = dataclasses.field(default_factory=dict)
    #: attr name -> lock kind ("lock"/"rlock"/"cond"/"sem").
    lock_attrs: dict[str, str] = dataclasses.field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.module.relpath}:{self.name}"

    def __hash__(self) -> int:
        return id(self)

    def __eq__(self, other: object) -> bool:
        return self is other

    def mro(self) -> Iterator["ClassInfo"]:
        """Self plus in-project bases, left-to-right depth-first."""
        seen: set[int] = set()
        stack = [self]
        while stack:
            c = stack.pop(0)
            if id(c) in seen:
                continue
            seen.add(id(c))
            yield c
            stack = list(c.bases) + stack

    def resolve_method(self, name: str) -> FuncInfo | None:
        for c in self.mro():
            if name in c.methods:
                return c.methods[name]
        return None

    def resolve_attr_type(self, name: str) -> TypeRef | None:
        for c in self.mro():
            t = c.attr_types.get(name)
            if t is not None:
                return None if t == _AMBIGUOUS else t
        return None

    def lock_decl(self, attr: str) -> "tuple[ClassInfo, str] | None":
        """(declaring class, kind) for a lock attribute — the declaring
        class gives the lock a stable identity shared by subclasses."""
        for c in self.mro():
            if attr in c.lock_attrs:
                return c, c.lock_attrs[attr]
        return None


@dataclasses.dataclass
class ModuleInfo:
    """One parsed file plus its import/def surface."""

    pf: ParsedFile
    relpath: str
    modname: str  # dotted module name derived from relpath
    imports: dict[str, str] = dataclasses.field(default_factory=dict)
    from_imports: dict[str, tuple[str, str]] = dataclasses.field(default_factory=dict)
    functions: dict[str, FuncInfo] = dataclasses.field(default_factory=dict)
    classes: dict[str, ClassInfo] = dataclasses.field(default_factory=dict)
    #: module-level lock variables: name -> kind.
    module_locks: dict[str, str] = dataclasses.field(default_factory=dict)
    #: module-level variable types (``_PLAN: FaultPlan | None = None``).
    var_types: dict[str, TypeRef] = dataclasses.field(default_factory=dict)


def _modname(relpath: str) -> str:
    name = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = [p for p in name.split("/") if p]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class ProjectIndex:
    """The whole-program view. Build once per lint run (memoized on the
    engine :class:`~hops_tpu.analysis.engine.Context`)."""

    def __init__(self, files: list[ParsedFile]):
        self.modules: dict[str, ModuleInfo] = {}  # relpath -> module
        self.by_modname: dict[str, ModuleInfo] = {}
        for pf in files:
            mod = ModuleInfo(pf=pf, relpath=pf.relpath, modname=_modname(pf.relpath))
            self.modules[pf.relpath] = mod
            self.by_modname[mod.modname] = mod
        for mod in self.modules.values():
            self._scan_module(mod)
        for mod in self.modules.values():
            self._resolve_bases(mod)
        for mod in self.modules.values():
            self._infer_types(mod)
        for mod in self.modules.values():
            self._register_guard_locks(mod)

    # -- pass 1: defs and imports --------------------------------------------

    def _scan_module(self, mod: ModuleInfo) -> None:
        for node in ast.walk(mod.pf.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    mod.imports[alias.asname or alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for alias in node.names:
                    mod.from_imports[alias.asname or alias.name] = (
                        node.module,
                        alias.name,
                    )
        for stmt in mod.pf.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mod.functions[stmt.name] = FuncInfo(
                    name=stmt.name, qualname=stmt.name, module=mod, node=stmt
                )
            elif isinstance(stmt, ast.ClassDef):
                cls = ClassInfo(name=stmt.name, module=mod, node=stmt)
                mod.classes[stmt.name] = cls
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        cls.methods[sub.name] = FuncInfo(
                            name=sub.name,
                            qualname=f"{stmt.name}.{sub.name}",
                            module=mod,
                            node=sub,
                            cls=cls,
                        )

    # -- pass 2: base classes -------------------------------------------------

    def _resolve_bases(self, mod: ModuleInfo) -> None:
        for cls in mod.classes.values():
            for base in cls.node.bases:
                t = self.resolve_type_expr(base, mod)
                if isinstance(t, ClassInfo):
                    cls.bases.append(t)

    # -- pass 3: attribute / variable types -----------------------------------

    def _infer_types(self, mod: ModuleInfo) -> None:
        for stmt in mod.pf.tree.body:
            if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
                t = self._annotation_type(stmt.annotation, mod)
                if t is not None:
                    self._record(mod.var_types, stmt.target.id, t)
            elif isinstance(stmt, ast.Assign) and stmt.value is not None:
                t = self._value_type(stmt.value, mod)
                if t is not None:
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            self._record(mod.var_types, tgt.id, t)
        for name, t in mod.var_types.items():
            if isinstance(t, str) and t in LOCK_TAGS:
                mod.module_locks[name] = t
        for cls in mod.classes.values():
            self._infer_class_attrs(cls)

    def _infer_class_attrs(self, cls: ClassInfo) -> None:
        for meth in cls.methods.values():
            params = self._param_types(meth)
            for node in ast.walk(meth.node):
                target = None
                value = None
                ann = None
                if isinstance(node, ast.Assign):
                    value = node.value
                    for t in node.targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            target = t.attr
                elif isinstance(node, ast.AnnAssign):
                    t = node.target
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        target = t.attr
                        ann = node.annotation
                        value = node.value
                if target is None:
                    continue
                inferred: TypeRef | None = None
                if ann is not None:
                    inferred = self._annotation_type(ann, cls.module)
                if inferred is None and value is not None:
                    inferred = self._value_type(value, cls.module)
                    if (
                        inferred is None
                        and isinstance(value, ast.Name)
                        and value.id in params
                    ):
                        inferred = params[value.id]
                if inferred is not None:
                    self._record(cls.attr_types, target, inferred)
        for attr, t in cls.attr_types.items():
            if isinstance(t, str) and t in LOCK_TAGS:
                cls.lock_attrs[attr] = t

    @staticmethod
    def _record(table: dict[str, TypeRef], name: str, t: TypeRef) -> None:
        prev = table.get(name)
        if prev is None or prev == t:
            table[name] = t
        elif prev != _AMBIGUOUS:
            table[name] = _AMBIGUOUS

    def _param_types(self, func: FuncInfo) -> dict[str, TypeRef]:
        out: dict[str, TypeRef] = {}
        args = func.node.args
        for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
            if a.annotation is not None:
                t = self._annotation_type(a.annotation, func.module)
                if t is not None:
                    out[a.arg] = t
        return out

    # -- guard-comment locks ---------------------------------------------------
    # ``self._idle = {}  # guarded by: self._lock`` declares ``_lock`` a
    # lock on that class even when its assignment is untyped (e.g.
    # ``self._lock = lock`` from an unannotated parameter).

    def _register_guard_locks(self, mod: ModuleInfo) -> None:
        for line, expr in mod.pf.guard_comments.items():
            try:
                parsed = ast.parse(expr.strip(), mode="eval").body
            except SyntaxError:
                continue
            if (
                isinstance(parsed, ast.Attribute)
                and isinstance(parsed.value, ast.Name)
                and parsed.value.id == "self"
            ):
                cls = self._class_at(mod, line)
                if cls is not None and parsed.attr not in cls.lock_attrs:
                    kind = cls.attr_types.get(parsed.attr)
                    cls.lock_attrs[parsed.attr] = (
                        kind if isinstance(kind, str) and kind in LOCK_TAGS else "lock"
                    )
            elif isinstance(parsed, ast.Name):
                mod.module_locks.setdefault(parsed.id, "lock")

    @staticmethod
    def _class_at(mod: ModuleInfo, line: int) -> ClassInfo | None:
        for cls in mod.classes.values():
            end = getattr(cls.node, "end_lineno", cls.node.lineno) or cls.node.lineno
            if cls.node.lineno <= line <= end:
                return cls
        return None

    # -- type resolution -------------------------------------------------------

    def resolve_type_name(self, dotted: str, mod: ModuleInfo) -> TypeRef | None:
        """Resolve a dotted type name in ``mod``'s namespace."""
        if not dotted:
            return None
        if dotted in BUILTIN_TAGS:
            return BUILTIN_TAGS[dotted]
        head, _, rest = dotted.partition(".")
        if not rest:
            if head in mod.classes:
                return mod.classes[head]
            if head in mod.from_imports:
                src_mod, orig = mod.from_imports[head]
                full = f"{src_mod}.{orig}"
                if full in BUILTIN_TAGS:
                    return BUILTIN_TAGS[full]
                target = self.by_modname.get(src_mod)
                if target is not None:
                    return target.classes.get(orig)
            return None
        if head in mod.imports:
            full = f"{mod.imports[head]}.{rest}"
            if full in BUILTIN_TAGS:
                return BUILTIN_TAGS[full]
            target = self.by_modname.get(mod.imports[head])
            if target is not None and "." not in rest:
                return target.classes.get(rest)
        if head in mod.from_imports:
            # ``from hops_tpu.runtime import httpclient`` style.
            src_mod, orig = mod.from_imports[head]
            full = f"{src_mod}.{orig}.{rest}"
            if full in BUILTIN_TAGS:
                return BUILTIN_TAGS[full]
            target = self.by_modname.get(f"{src_mod}.{orig}")
            if target is not None and "." not in rest:
                return target.classes.get(rest)
        return None

    def resolve_type_expr(self, node: ast.AST, mod: ModuleInfo) -> TypeRef | None:
        return self.resolve_type_name(dotted_name(node), mod)

    def _annotation_type(self, ann: ast.AST, mod: ModuleInfo) -> TypeRef | None:
        """Best-effort type from an annotation: handles string forms,
        ``X | None`` unions, and ``Optional[X]``; containers are skipped."""
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value.strip(), mode="eval").body
            except SyntaxError:
                return None
        if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
            return self._annotation_type(ann.left, mod) or self._annotation_type(
                ann.right, mod
            )
        if isinstance(ann, ast.Subscript):
            base = dotted_name(ann.value)
            if base.split(".")[-1] == "Optional":
                return self._annotation_type(ann.slice, mod)
            return None
        if isinstance(ann, (ast.Name, ast.Attribute)):
            return self.resolve_type_expr(ann, mod)
        return None

    def _value_type(self, value: ast.AST, mod: ModuleInfo) -> TypeRef | None:
        """Type of an assigned value: ``ClassName(...)`` constructions
        and calls to functions with resolvable return annotations."""
        if not isinstance(value, ast.Call):
            return None
        t = self.resolve_type_expr(value.func, mod)
        if t is not None:
            return t
        return None

    # -- expression typing inside a function ----------------------------------

    def local_env(self, func: FuncInfo) -> dict[str, TypeRef]:
        """Parameter annotations plus simple ``x = <typed expr>``
        assignments, in a single forward pass."""
        env = self._param_types(func)
        for node in ast.walk(func.node):
            if isinstance(node, ast.Assign):
                t = self.infer_expr_type(node.value, env, func)
                if t is not None:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            env[tgt.id] = t
            elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
                t = self._annotation_type(node.annotation, func.module)
                if t is not None:
                    env[node.target.id] = t
        return env

    def infer_expr_type(
        self,
        expr: ast.AST,
        env: dict[str, TypeRef],
        func: FuncInfo,
        depth: int = 0,
    ) -> TypeRef | None:
        if depth > 6:
            return None
        mod = func.module
        if isinstance(expr, ast.Name):
            if expr.id == "self" and func.cls is not None:
                return func.cls
            t = env.get(expr.id)
            if t is not None:
                return t
            t = mod.var_types.get(expr.id)
            if t is not None and t != _AMBIGUOUS:
                return t
            return None
        if isinstance(expr, ast.Attribute):
            base = self.infer_expr_type(expr.value, env, func, depth + 1)
            if isinstance(base, ClassInfo):
                return base.resolve_attr_type(expr.attr)
            return None
        if isinstance(expr, ast.Call):
            t = self.resolve_type_expr(expr.func, mod)
            if t is not None:
                return t
            callee = self.resolve_call(expr, func, env)
            if callee is not None and callee.node.returns is not None:
                return self._annotation_type(callee.node.returns, callee.module)
            return None
        return None

    # -- call resolution -------------------------------------------------------

    def resolve_call(
        self, call: ast.Call, func: FuncInfo, env: dict[str, TypeRef]
    ) -> FuncInfo | None:
        """Resolve a call site to a project function, or ``None``.

        ``ClassName(...)`` resolves to the class ``__init__`` (searching
        project bases) so constructor work composes into the graph."""
        f = call.func
        mod = func.module
        if isinstance(f, ast.Name):
            if f.id in mod.functions:
                return mod.functions[f.id]
            t = self.resolve_type_name(f.id, mod)
            if isinstance(t, ClassInfo):
                return t.resolve_method("__init__")
            if f.id in mod.from_imports:
                src_mod, orig = mod.from_imports[f.id]
                target = self.by_modname.get(src_mod)
                if target is not None:
                    return target.functions.get(orig)
            return None
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name):
                alias = f.value.id
                if alias in mod.imports:
                    target = self.by_modname.get(mod.imports[alias])
                    if target is not None:
                        if f.attr in target.functions:
                            return target.functions[f.attr]
                        t = target.classes.get(f.attr)
                        if t is not None:
                            return t.resolve_method("__init__")
                    return None
                if alias in mod.from_imports and alias not in env:
                    src_mod, orig = mod.from_imports[alias]
                    target = self.by_modname.get(f"{src_mod}.{orig}")
                    if target is not None:
                        if f.attr in target.functions:
                            return target.functions[f.attr]
                        t = target.classes.get(f.attr)
                        if t is not None:
                            return t.resolve_method("__init__")
                    return None
            base_t = self.infer_expr_type(f.value, env, func)
            if isinstance(base_t, ClassInfo):
                return base_t.resolve_method(f.attr)
            return None
        return None

    # -- iteration -------------------------------------------------------------

    def functions(self) -> Iterator[FuncInfo]:
        for mod in self.modules.values():
            yield from mod.functions.values()
            for cls in mod.classes.values():
                yield from cls.methods.values()
