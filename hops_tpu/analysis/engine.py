"""graftlint rule engine: walk, parse, run rules, apply suppressions.

Rules subclass :class:`Rule` and register with :func:`register`. Two
hooks: :meth:`Rule.check_file` for file-local rules and
:meth:`Rule.check_project` for whole-tree invariants (metric-name
consistency needs every registration site before it can judge any).
The engine is deliberately dumb about ordering — findings are sorted
``(path, line, col, rule)`` at the end so output is stable regardless
of rule registration order.
"""

from __future__ import annotations

import ast
import dataclasses
import tokenize
from pathlib import Path
from typing import Iterable, Iterator

from hops_tpu.analysis.model import Finding, ParsedFile


@dataclasses.dataclass
class Context:
    """Project-level inputs shared by every rule."""

    root: Path  # lint root findings' paths are relative to
    docs_path: Path | None = None  # docs/operations.md for metric checks
    #: Per-run scratch shared across rules — the concurrency rules
    #: memoize one whole-program model here instead of building three.
    cache: dict = dataclasses.field(default_factory=dict)

    def docs_text(self) -> str | None:
        if self.docs_path is not None and self.docs_path.is_file():
            return self.docs_path.read_text()
        return None


class Rule:
    """One named check. ``name`` is the id used in findings, inline
    ``# graftlint: disable=`` pragmas, and baseline entries."""

    name: str = ""
    description: str = ""

    def check_file(self, pf: ParsedFile, ctx: Context) -> Iterable[Finding]:
        return ()

    def check_project(
        self, files: list[ParsedFile], ctx: Context
    ) -> Iterable[Finding]:
        return ()


_RULES: dict[str, Rule] = {}


def register(rule_cls: type[Rule]) -> type[Rule]:
    """Class decorator: instantiate and register a rule by its name."""
    rule = rule_cls()
    if not rule.name:
        raise ValueError(f"{rule_cls.__name__} has no rule name")
    if rule.name in _RULES:
        raise ValueError(f"duplicate rule name {rule.name!r}")
    _RULES[rule.name] = rule
    return rule_cls


def all_rules() -> list[Rule]:
    """Registered rules, importing the built-in set on first use."""
    import hops_tpu.analysis.rules  # noqa: F401 — registration side effect

    return [_RULES[k] for k in sorted(_RULES)]


def iter_py_files(paths: Iterable[Path]) -> Iterator[Path]:
    seen: set[Path] = set()  # a file named directly AND via its parent dir
    for p in paths:
        if p.is_dir():
            candidates = sorted(
                f for f in p.rglob("*.py") if "__pycache__" not in f.parts
            )
        elif p.suffix == ".py":
            candidates = [p]
        else:
            continue
        for f in candidates:
            key = f.resolve()
            if key not in seen:
                seen.add(key)
                yield f


def parse_files(paths: Iterable[Path], root: Path) -> list[ParsedFile]:
    """Parse every ``.py`` under ``paths``; files that do not parse are
    reported by the caller via :class:`ParseError`."""
    out: list[ParsedFile] = []
    for f in iter_py_files(paths):
        try:
            rel = f.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = f.as_posix()
        try:
            # tokenize.open honors PEP 263 coding cookies + BOMs, which
            # plain read_text (always-UTF-8) would crash on.
            with tokenize.open(f) as fh:
                source = fh.read()
            out.append(ParsedFile(f, rel, source))
        except (SyntaxError, ValueError, UnicodeDecodeError, OSError) as e:
            # ValueError: ast.parse on source with NUL bytes.
            raise ParseError(f"{f}: {e}") from e
    return out


class ParseError(RuntimeError):
    """A lint target failed to parse — a usage error, not a finding."""


def run(
    paths: Iterable[Path],
    root: Path | None = None,
    docs_path: Path | None = None,
    rules: Iterable[Rule] | None = None,
    focus: Iterable[Path] | None = None,
) -> list[Finding]:
    """Lint ``paths`` and return suppression-filtered findings.

    Baseline filtering is the caller's job (:mod:`.baseline`): the
    engine only honors inline/file pragmas, so ``--write-baseline``
    sees exactly the findings a baseline could absorb.

    ``focus`` (``--changed``) restricts REPORTING to those files while
    keeping the whole-program rules sound: file-local rules simply skip
    unfocused files, but project rules still analyze every parsed file
    (a lock graph built from a diff would miss the cross-file half of
    an inversion) and only their findings are filtered afterwards — a
    project finding survives when its anchor file OR any file in
    ``Finding.related`` (its cross-file evidence) is focused.
    """
    paths = [Path(p) for p in paths]
    if root is None:
        root = _common_root(paths)
    ctx = Context(root=root, docs_path=docs_path)
    files = parse_files(paths, root)
    by_path = {pf.relpath: pf for pf in files}
    focus_keys: set[Path] | None = None
    if focus is not None:
        focus_keys = {Path(p).resolve() for p in focus}
    active = list(rules) if rules is not None else all_rules()
    findings: list[Finding] = []
    for rule in active:
        for pf in files:
            if focus_keys is not None and pf.path.resolve() not in focus_keys:
                continue
            for finding in rule.check_file(pf, ctx):
                if not pf.suppressed(rule.name, finding.line):
                    findings.append(finding)
        for finding in rule.check_project(files, ctx):
            pf = by_path.get(finding.path)
            if pf is not None and pf.suppressed(finding.rule, finding.line):
                continue
            if focus_keys is not None:
                involved = [finding.path, *finding.related]
                if not any(
                    rp in by_path and by_path[rp].path.resolve() in focus_keys
                    for rp in involved
                ):
                    continue
            findings.append(finding)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def _common_root(paths: list[Path]) -> Path:
    resolved = [p.resolve() if p.is_dir() else p.resolve().parent for p in paths]
    if not resolved:
        return Path.cwd()
    root = resolved[0]
    for p in resolved[1:]:
        while not p.is_relative_to(root):
            root = root.parent
    return root


# -- shared AST helpers used by several rules ---------------------------------


def call_name(node: ast.AST) -> str:
    """Terminal name of a call target: ``jax.jit`` -> ``jit``,
    ``print`` -> ``print``; empty string for anything else."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def dotted_name(node: ast.AST) -> str:
    """``a.b.c`` for nested Name/Attribute chains, else ``''``."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def assigned_names(target: ast.AST) -> set[str]:
    """Plain names bound by an assignment target (tuple-unpack aware)."""
    out: set[str] = set()
    for node in ast.walk(target):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            out.add(node.id)
    return out


def root_name(node: ast.AST) -> ast.AST:
    """Strip Subscript/Attribute layers: ``metrics['loss']`` ->
    ``metrics`` (the Name), ``step(s, b)[1]`` -> the Call."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    return node
