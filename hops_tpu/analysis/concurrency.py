"""Global lock-acquisition graph and blocking-call summaries.

Built on :mod:`.project`, this module computes, once per lint run:

- **Lock identities.** A class-attribute lock is
  ``<declaring file>:<Class>.<attr>`` (declaring class, so subclasses
  share the base's lock identity); a module-level lock is
  ``<file>:<name>``. Locks without a stable global identity (locals,
  unannotated parameters) still count as "a lock is held" for
  blocking-under-lock but never enter the order graph.
- **Per-function summaries** via fixpoint over the call graph:
  ``acquires[f]`` — locks ``f`` takes directly or transitively, and
  ``blocks[f]`` — blocking operations ``f`` can reach, each with a
  witness chain of ``file:line`` steps. A ``cv.wait()`` records the
  condition's own lock as *waived*: waiting releases that lock, so
  holding it across the wait is the sanctioned consumer shape.
- **The lock graph.** While lock A is held (lexically ``with A:`` or a
  ``# guarded by: A`` annotation on the ``def`` line), any lock B
  acquired — directly or through a resolved call — adds edge A→B.
  Cycles are lock-order inversions.
- **Selector-loop reachability.** A class owning a
  ``selectors.DefaultSelector()`` attribute defines an event loop; the
  method calling ``.select()`` on it is the loop root. Everything
  reachable from the root runs on the IO thread and must never block —
  worker-thread handoff (``Thread(target=...)``/queue+notify) is
  invisible to the call graph, which is exactly the sanctioned escape.

Everything here under-approximates: unresolved calls and unknown
receivers contribute nothing, so a missed edge can only hide a finding.
The blocking-operation list is the small closed set the serving stack
actually uses (``HTTPPool``, ``urlopen``/sockets, ``subprocess``,
``time.sleep``, ``with_deadline``, kvstore FFI, ``fsync``, cv/event
waits, ``Thread.join``); everything else (``faultinject.fire`` →
``time.sleep``, ...) is derived transitively.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator

from hops_tpu.analysis.engine import Context, call_name, dotted_name
from hops_tpu.analysis.model import ParsedFile
from hops_tpu.analysis.project import (
    LOCK_TAGS,
    ClassInfo,
    FuncInfo,
    ProjectIndex,
    TypeRef,
)

#: Project functions that ARE blocking primitives: their internals use
#: untyped stdlib objects the inference cannot follow, so name them
#: here instead of relying on derivation. Keyed (module basename,
#: qualname).
PROJECT_BLOCKING: dict[tuple[str, str], str] = {
    ("httpclient", "HTTPPool.request"): "HTTPPool.request (network I/O)",
    ("httpclient", "HTTPPool.pipeline"): "HTTPPool.pipeline (network I/O)",
    ("httpclient", "HTTPPool.get_many"): "HTTPPool.get_many (network I/O)",
    ("resilience", "with_deadline"): "with_deadline (bounded worker wait)",
}

ChainStep = tuple[str, int, str]  # (relpath, line, description)


def _fmt_chain(chain: list[ChainStep]) -> str:
    return "\n".join(f"{p}:{ln}  {txt}" for p, ln, txt in chain)


@dataclasses.dataclass(frozen=True)
class LockRef:
    """One lock as held/acquired at a program point."""

    id: str  # stable id, or a per-function id when not global
    kind: str  # lock/rlock/cond/sem
    global_: bool  # participates in the order graph
    step: ChainStep  # where it was acquired (or the guarded def line)


@dataclasses.dataclass(frozen=True)
class BlockOp:
    label: str
    waived: str | None = None  # lock id released by the wait itself


@dataclasses.dataclass(frozen=True)
class Obs:
    """One observation from the lexical walk of a function body."""

    kind: str  # "acquire" | "call" | "block"
    held: tuple[LockRef, ...]
    step: ChainStep
    lock: LockRef | None = None
    callee: FuncInfo | None = None
    block: BlockOp | None = None


@dataclasses.dataclass
class LoopStall:
    root: FuncInfo
    func: FuncInfo
    block: BlockOp
    step: ChainStep
    chain: list[ChainStep]


@dataclasses.dataclass
class HeldBlock:
    func: FuncInfo
    lock: LockRef
    block: BlockOp
    step: ChainStep
    chain: list[ChainStep]


@dataclasses.dataclass
class Inversion:
    a: str
    b: str
    chain_ab: list[ChainStep]
    chain_ba: list[ChainStep]
    func_ab: str  # qualnames owning each direction
    func_ba: str


class ConcurrencyModel:
    """All concurrency facts for one lint run."""

    def __init__(self, project: ProjectIndex):
        self.project = project
        self.obs: dict[FuncInfo, list[Obs]] = {}
        #: transitively acquired global locks: f -> lock id -> (kind, chain)
        self.acquires: dict[FuncInfo, dict[str, tuple[str, list[ChainStep]]]] = {}
        #: transitively reachable blocking ops: f -> BlockOp -> chain
        self.blocks: dict[FuncInfo, dict[BlockOp, list[ChainStep]]] = {}
        self.calls: dict[FuncInfo, list[tuple[FuncInfo, ChainStep]]] = {}
        #: lock graph: (a, b) -> (chain, qualname of the acquiring function)
        self.edges: dict[tuple[str, str], tuple[list[ChainStep], str]] = {}
        self.lock_kinds: dict[str, str] = {}
        self._anon = 0
        for func in project.functions():
            self.obs[func] = self._scan(func)
        self._fixpoint()
        self._build_edges()

    # -- lexical scan ----------------------------------------------------------

    def _scan(self, func: FuncInfo) -> list[Obs]:
        env = self.project.local_env(func)
        out: list[Obs] = []
        held: list[LockRef] = list(self._entry_holds(func, env))

        def step(node: ast.AST, text: str) -> ChainStep:
            return (func.module.relpath, getattr(node, "lineno", 1), text)

        def classify(call: ast.Call) -> None:
            block = self._blocking(call, func, env)
            if block is not None:
                out.append(
                    Obs(
                        kind="block",
                        held=tuple(held),
                        step=step(call, f"blocking {block.label}"),
                        block=block,
                    )
                )
                return
            callee = self.project.resolve_call(call, func, env)
            if callee is None:
                return
            label = PROJECT_BLOCKING.get(
                (callee.module.modname.split(".")[-1], callee.qualname)
            )
            if label is not None:
                out.append(
                    Obs(
                        kind="block",
                        held=tuple(held),
                        step=step(call, f"blocking {label}"),
                        block=BlockOp(label),
                    )
                )
                return
            out.append(
                Obs(
                    kind="call",
                    held=tuple(held),
                    step=step(call, f"calls {dotted_name(call.func) or callee.name}()"),
                    callee=callee,
                )
            )

        def visit(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return  # nested scope: analyzed separately, closures unmodeled
            if isinstance(node, (ast.With, ast.AsyncWith)):
                added = 0
                for item in node.items:
                    lk = self._lock_from_expr(
                        item.context_expr, func, env,
                        step(item.context_expr,
                             f"with {dotted_name(item.context_expr) or 'lock'}"),
                    )
                    if lk is not None:
                        out.append(
                            Obs(kind="acquire", held=tuple(held), step=lk.step, lock=lk)
                        )
                        held.append(lk)
                        added += 1
                    else:
                        visit(item.context_expr)
                for stmt in node.body:
                    visit(stmt)
                for _ in range(added):
                    held.pop()
                return
            if isinstance(node, ast.Call):
                classify(node)
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in func.node.body:
            visit(stmt)
        return out

    def _entry_holds(
        self, func: FuncInfo, env: dict[str, TypeRef]
    ) -> Iterator[LockRef]:
        """``# guarded by:`` on the ``def`` line = caller holds the lock
        for the whole body (the lock-discipline helper shape)."""
        pf = func.module.pf
        node = func.node
        first = node.body[0].lineno if node.body else node.lineno
        for line, expr in pf.guard_comments.items():
            if not (node.lineno <= line < max(first, node.lineno + 1)):
                continue
            try:
                parsed = ast.parse(expr.strip(), mode="eval").body
            except SyntaxError:
                continue
            lk = self._lock_from_expr(
                parsed, func, env,
                (pf.relpath, node.lineno, f"def {func.qualname} (guarded by: {expr})"),
            )
            if lk is not None:
                yield lk

    # -- lock identity ---------------------------------------------------------

    def _lock_from_expr(
        self,
        expr: ast.AST,
        func: FuncInfo,
        env: dict[str, TypeRef],
        step: ChainStep,
    ) -> LockRef | None:
        mod = func.module
        if isinstance(expr, ast.Name):
            kind = mod.module_locks.get(expr.id)
            if kind is not None:
                lock_id = f"{mod.relpath}:{expr.id}"
                self.lock_kinds[lock_id] = kind
                return LockRef(lock_id, kind, True, step)
            t = env.get(expr.id)
            if isinstance(t, str) and t in LOCK_TAGS:
                # A lock passed in or created locally: held, but no
                # stable cross-function identity.
                self._anon += 1
                return LockRef(f"{func.key}:<{expr.id}#{self._anon}>", t, False, step)
            return None
        if isinstance(expr, ast.Attribute):
            base_t = self.project.infer_expr_type(expr.value, env, func)
            if isinstance(base_t, ClassInfo):
                decl = base_t.lock_decl(expr.attr)
                if decl is not None:
                    owner, kind = decl
                    lock_id = f"{owner.key}.{expr.attr}"
                    self.lock_kinds[lock_id] = kind
                    return LockRef(lock_id, kind, True, step)
        return None

    # -- blocking primitives ---------------------------------------------------

    def _blocking(
        self, call: ast.Call, func: FuncInfo, env: dict[str, TypeRef]
    ) -> BlockOp | None:
        f = call.func
        dotted = dotted_name(f)
        last = call_name(f)
        mod = func.module
        head = dotted.split(".")[0] if dotted else ""

        def module_is(name: str) -> bool:
            if mod.imports.get(head) == name:
                return True
            src = mod.from_imports.get(last)
            return src is not None and src[0] == name and src[1] == last

        if last == "sleep" and (dotted == "time.sleep" or module_is("time")):
            return BlockOp("time.sleep")
        if last == "urlopen":
            return BlockOp("urlopen (network I/O)")
        if last == "create_connection" and (module_is("socket") or head == "socket"):
            return BlockOp("socket.create_connection")
        if last in ("run", "call", "check_call", "check_output", "Popen") and (
            module_is("subprocess") or head == "subprocess"
        ):
            return BlockOp(f"subprocess.{last}")
        if last == "communicate":
            return BlockOp("Popen.communicate")
        if last == "fsync" and (module_is("os") or head == "os"):
            return BlockOp("os.fsync")
        if last.startswith("kv_") and "._lib." in f"{dotted}.":
            return BlockOp(f"kvstore FFI {last}")
        if isinstance(f, ast.Attribute):
            recv = self.project.infer_expr_type(f.value, env, func)
            if last in ("wait", "wait_for"):
                if recv == "cond":
                    cv = self._lock_from_expr(f.value, func, env, ("", 0, ""))
                    return BlockOp(
                        "Condition.wait", waived=cv.id if cv is not None else None
                    )
                if recv == "event":
                    return BlockOp("Event.wait")
                if recv == "popen":
                    return BlockOp("Popen.wait")
            if last == "join" and recv == "thread":
                return BlockOp("Thread.join")
            if last == "sendall":
                return BlockOp("socket.sendall")
        return None

    # -- fixpoint propagation --------------------------------------------------

    def _fixpoint(self) -> None:
        for func, obs in self.obs.items():
            acq: dict[str, tuple[str, list[ChainStep]]] = {}
            blk: dict[BlockOp, list[ChainStep]] = {}
            calls: list[tuple[FuncInfo, ChainStep]] = []
            for o in obs:
                if o.kind == "acquire" and o.lock is not None and o.lock.global_:
                    acq.setdefault(o.lock.id, (o.lock.kind, [o.step]))
                elif o.kind == "block" and o.block is not None:
                    blk.setdefault(o.block, [o.step])
                elif o.kind == "call" and o.callee is not None:
                    calls.append((o.callee, o.step))
            self.acquires[func] = acq
            self.blocks[func] = blk
            self.calls[func] = calls
        changed = True
        while changed:
            changed = False
            for func in self.obs:
                acq = self.acquires[func]
                blk = self.blocks[func]
                for callee, step in self.calls[func]:
                    if callee not in self.acquires:
                        continue
                    for lock_id, (kind, chain) in self.acquires[callee].items():
                        if lock_id not in acq:
                            acq[lock_id] = (kind, [step] + chain)
                            changed = True
                    for op, chain in self.blocks[callee].items():
                        if op not in blk:
                            blk[op] = [step] + chain
                            changed = True

    # -- the lock graph --------------------------------------------------------

    def _build_edges(self) -> None:
        for func, obs in self.obs.items():
            for o in obs:
                held_global = [h for h in o.held if h.global_]
                if o.kind == "acquire" and o.lock is not None and o.lock.global_:
                    for h in held_global:
                        self._edge(h.id, o.lock.id, [h.step, o.step], func.qualname)
                elif o.kind == "call" and o.callee is not None and held_global:
                    for lock_id, (kind, chain) in self.acquires.get(
                        o.callee, {}
                    ).items():
                        for h in held_global:
                            self._edge(
                                h.id, lock_id, [h.step, o.step] + chain, func.qualname
                            )

    def _edge(
        self, a: str, b: str, chain: list[ChainStep], qualname: str
    ) -> None:
        if a == b:
            return  # re-entry: RLock by design, plain-Lock self-deadlock
            # is a different (single-lock) defect than an order inversion
        key = (a, b)
        if key not in self.edges or len(chain) < len(self.edges[key][0]):
            self.edges[key] = (chain, qualname)

    # -- rule surfaces ---------------------------------------------------------

    def inversions(self) -> list[Inversion]:
        """Cycles in the lock graph. Two-lock cycles (the classic AB/BA
        inversion) are reported pairwise; longer cycles fall out as
        chains of pairwise reports once any two members invert, and any
        remaining pure N-cycle is reported on its lexicographically
        first edge."""
        out: list[Inversion] = []
        seen: set[tuple[str, str]] = set()
        for (a, b), (chain_ab, fn_ab) in sorted(self.edges.items()):
            if (b, a) not in self.edges or (b, a) in seen:
                continue
            seen.add((a, b))
            chain_ba, fn_ba = self.edges[(b, a)]
            out.append(Inversion(a, b, chain_ab, chain_ba, fn_ab, fn_ba))
        covered = {n for inv in out for n in (inv.a, inv.b)}
        for cycle in self._simple_cycles():
            if len(cycle) < 3 or any(n in covered for n in cycle):
                continue  # 2-cycles already reported pairwise above
            covered.update(cycle)
            a, b = cycle[0], cycle[1]
            chain_ab, fn_ab = self.edges[(a, b)]
            back: list[ChainStep] = []
            for x, y in zip(cycle[1:], cycle[2:] + [a]):
                back.extend(self.edges[(x, y)][0])
            out.append(
                Inversion(a, b, chain_ab, back, fn_ab, self.edges[(b, cycle[2])][1])
            )
        return out

    def _simple_cycles(self) -> list[list[str]]:
        adj: dict[str, list[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, []).append(b)
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        on_stack: set[str] = set()
        stack: list[str] = []
        sccs: list[list[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            for w in adj.get(v, ()):
                if w not in index:
                    strongconnect(w)
                    low[v] = min(low[v], low[w])
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))
        for v in sorted(adj):
            if v not in index:
                strongconnect(v)
        cycles: list[list[str]] = []
        for comp in sccs:
            members = set(comp)
            start = comp[0]
            path = [start]
            seen_nodes = {start}
            node = start
            while True:  # walk any in-SCC successor until we loop
                nxt = next(
                    (w for w in sorted(adj.get(node, ())) if w in members), None
                )
                if nxt is None:
                    break
                if nxt == start:
                    cycles.append(path)
                    break
                if nxt in seen_nodes:
                    cycles.append(path[path.index(nxt):])
                    break
                path.append(nxt)
                seen_nodes.add(nxt)
                node = nxt
        return [c for c in cycles if len(c) > 1]

    def held_blocks(self) -> list[HeldBlock]:
        """Blocking ops reached while a lock is held, one report per
        (function, lock, op label)."""
        out: list[HeldBlock] = []
        seen: set[tuple[str, str, str]] = set()
        for func, obs in self.obs.items():
            for o in obs:
                if not o.held:
                    continue
                if o.kind == "block" and o.block is not None:
                    candidates = [(o.block, [o.step])]
                elif o.kind == "call" and o.callee is not None:
                    candidates = [
                        (op, [o.step] + chain)
                        for op, chain in self.blocks.get(o.callee, {}).items()
                    ]
                else:
                    continue
                for op, chain in candidates:
                    for h in o.held:
                        if op.waived is not None and op.waived == h.id:
                            continue
                        key = (func.key, h.id, op.label)
                        if key in seen:
                            continue
                        seen.add(key)
                        out.append(HeldBlock(func, h, op, o.step, [h.step] + chain))
        return out

    # -- selector-loop reachability -------------------------------------------

    def loop_roots(self) -> list[FuncInfo]:
        roots: list[FuncInfo] = []
        for mod in self.project.modules.values():
            for cls in mod.classes.values():
                sel_attrs = {
                    a for a, t in cls.attr_types.items() if t == "selector"
                }
                if not sel_attrs:
                    continue
                for meth in cls.methods.values():
                    env = self.project.local_env(meth)
                    for node in ast.walk(meth.node):
                        if (
                            isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and node.func.attr == "select"
                            and self.project.infer_expr_type(
                                node.func.value, env, meth
                            )
                            == "selector"
                        ):
                            roots.append(meth)
                            break
        return roots

    def loop_stalls(self) -> list[LoopStall]:
        out: list[LoopStall] = []
        seen: set[tuple[str, str, str]] = set()
        for root in self.loop_roots():
            # BFS with parent pointers for the witness chain.
            parent: dict[FuncInfo, tuple[FuncInfo, ChainStep] | None] = {root: None}
            queue = [root]
            while queue:
                func = queue.pop(0)
                for callee, step in self.calls.get(func, ()):
                    if callee not in parent:
                        parent[callee] = (func, step)
                        queue.append(callee)
            for func in parent:
                for o in self.obs.get(func, ()):
                    if o.kind != "block" or o.block is None:
                        continue
                    key = (root.key, func.key, o.block.label)
                    if key in seen:
                        continue
                    seen.add(key)
                    chain: list[ChainStep] = [o.step]
                    node = func
                    while parent[node] is not None:
                        node, step = parent[node]  # type: ignore[misc]
                        chain.insert(0, step)
                    chain.insert(
                        0,
                        (
                            root.module.relpath,
                            root.node.lineno,
                            f"selector loop root {root.qualname}",
                        ),
                    )
                    out.append(LoopStall(root, func, o.block, o.step, chain))
        return out

    # -- --graph lock dumps ----------------------------------------------------

    def graph_dict(self) -> dict:
        return {
            "locks": [
                {"id": lock_id, "kind": kind}
                for lock_id, kind in sorted(self.lock_kinds.items())
            ],
            "edges": [
                {
                    "from": a,
                    "to": b,
                    "function": qualname,
                    "chain": [
                        {"path": p, "line": ln, "step": txt} for p, ln, txt in chain
                    ],
                }
                for (a, b), (chain, qualname) in sorted(self.edges.items())
            ],
        }

    def graph_dot(self) -> str:
        lines = ["digraph lock_order {"]
        for lock_id, kind in sorted(self.lock_kinds.items()):
            lines.append(f'  "{lock_id}" [label="{lock_id}\\n({kind})"];')
        for (a, b), (chain, qualname) in sorted(self.edges.items()):
            p, ln, _ = chain[-1]
            lines.append(f'  "{a}" -> "{b}" [label="{qualname} {p}:{ln}"];')
        lines.append("}")
        return "\n".join(lines)


def get_model(files: list[ParsedFile], ctx: Context) -> ConcurrencyModel:
    """The per-run memoized model (three rules share one computation)."""
    cached = ctx.cache.get("concurrency")
    if cached is None:
        cached = ConcurrencyModel(ProjectIndex(files))
        ctx.cache["concurrency"] = cached
    return cached
