"""Scenario synthesis: workload artifacts for what capture can't see.

Capture replays yesterday; these generators write artifacts in the
SAME schema (via :class:`WorkloadRecorder`, so rotation/manifest/
verification are one code path) for the traffic shapes worth testing
before they happen:

- ``diurnal`` — a sinusoidal rate ramp (trough → peak → trough over
  ``duration_s``): does autoscaling track the curve or oscillate?
- ``herd`` — steady load with a thundering-herd burst at the midpoint
  (the post-rollout reconnect stampede): does admission shed or
  collapse?
- ``hot_key`` — feature-join entity IDs with hot-key skew
  (``hot_frac`` of requests hit ``hot_keys`` entities): does the
  online store's sharding melt on one shard?
- ``tenant_spray`` — adversarial unique-tenant-per-request spray: do
  per-tenant rate limits and metric labels stay bounded?

Every generator is fully seeded (SHA-256-derived RNG, the replay
engine's discipline): same params + seed ⇒ byte-identical artifact.
Records carry arrival times, tenants, and payload shapes but no
outcomes (``status``/``latency_ms`` absent) — the recorded-vs-replayed
comparison simply omits its recorded column for synthetic artifacts.
"""

from __future__ import annotations

import hashlib
import math
import random
from pathlib import Path
from typing import Any, Callable

from hops_tpu.telemetry.workload.capture import WorkloadRecorder


def _rng(seed: int, scenario: str) -> random.Random:
    digest = hashlib.sha256(f"synth:{scenario}:{seed}".encode()).digest()
    return random.Random(int.from_bytes(digest[:8], "big"))


def _poisson_arrivals(rng: random.Random, rate_fn: Callable[[float], float],
                      duration_s: float, bins: int = 256) -> list[float]:
    """Inhomogeneous Poisson arrivals by per-bin thinning: split
    ``duration_s`` into ``bins``, draw a Poisson-ish count at the
    bin-center rate, place arrivals uniformly inside the bin."""
    arrivals: list[float] = []
    dt = duration_s / bins
    for b in range(bins):
        t_mid = (b + 0.5) * dt
        lam = max(0.0, rate_fn(t_mid)) * dt
        # Knuth's method is exact and stdlib-only; lam stays small
        # because bins are fine-grained.
        n, p, threshold = 0, 1.0, math.exp(-lam)
        while True:
            p *= rng.random()
            if p <= threshold:
                break
            n += 1
        arrivals.extend(b * dt + rng.random() * dt for _ in range(n))
    arrivals.sort()
    return arrivals


def _dense_payload(rng: random.Random, width: int = 4) -> dict[str, Any]:
    return {"instances": [[round(rng.uniform(-1.0, 1.0), 6)
                           for _ in range(width)]]}


def _synth_diurnal(rng: random.Random, p: dict[str, Any]) -> list[dict[str, Any]]:
    duration, base, peak = p["duration_s"], p["base_rps"], p["peak_factor"]

    def rate(t: float) -> float:
        # Trough at t=0 and t=duration, peak at the midpoint.
        return base * (1.0 + (peak - 1.0) * 0.5
                       * (1.0 - math.cos(2.0 * math.pi * t / duration)))

    return [
        {"t": t, "tenant": p["tenants"][i % len(p["tenants"])],
         "payload": _dense_payload(rng)}
        for i, t in enumerate(_poisson_arrivals(rng, rate, duration))
    ]


def _synth_herd(rng: random.Random, p: dict[str, Any]) -> list[dict[str, Any]]:
    duration, base = p["duration_s"], p["base_rps"]
    rows = [
        {"t": t, "tenant": p["tenants"][i % len(p["tenants"])],
         "payload": _dense_payload(rng)}
        for i, t in enumerate(
            _poisson_arrivals(rng, lambda _t: base, duration))
    ]
    # The stampede: burst_size arrivals inside burst_window_s at the
    # midpoint — the reconnect herd after a rollout flips the fleet.
    t_burst = duration * 0.5
    rows.extend(
        {"t": t_burst + rng.random() * p["burst_window_s"],
         "tenant": "herd",
         "payload": _dense_payload(rng)}
        for _ in range(p["burst_size"])
    )
    rows.sort(key=lambda r: r["t"])
    return rows


def _synth_hot_key(rng: random.Random, p: dict[str, Any]) -> list[dict[str, Any]]:
    duration, base = p["duration_s"], p["base_rps"]
    hot = list(range(p["hot_keys"]))
    rows = []
    for i, t in enumerate(_poisson_arrivals(rng, lambda _t: base, duration)):
        entities = []
        for _ in range(p["batch"]):
            if rng.random() < p["hot_frac"]:
                key = hot[rng.randrange(len(hot))]
            else:
                key = rng.randrange(p["entities"])
            entities.append({p["entity_key"]: key})
        rows.append({
            "t": t, "tenant": p["tenants"][i % len(p["tenants"])],
            "payload": {"instances": entities}, "entities": entities,
        })
    return rows


def _synth_tenant_spray(rng: random.Random,
                        p: dict[str, Any]) -> list[dict[str, Any]]:
    duration, base = p["duration_s"], p["base_rps"]
    return [
        {"t": t, "tenant": f"spray-{i:06d}",
         "payload": _dense_payload(rng)}
        for i, t in enumerate(
            _poisson_arrivals(rng, lambda _t: base, duration))
    ]


#: Scenario catalog: name -> (generator, default params). Keep in sync
#: with docs/operations.md "Workload capture & replay".
SCENARIOS: dict[str, tuple[Callable[..., list[dict[str, Any]]],
                           dict[str, Any]]] = {
    "diurnal": (_synth_diurnal, {
        "duration_s": 60.0, "base_rps": 5.0, "peak_factor": 6.0,
        "tenants": ["interactive", "batch"],
    }),
    "herd": (_synth_herd, {
        "duration_s": 30.0, "base_rps": 4.0, "burst_size": 100,
        "burst_window_s": 0.25, "tenants": ["interactive"],
    }),
    "hot_key": (_synth_hot_key, {
        "duration_s": 30.0, "base_rps": 8.0, "entities": 4096,
        "hot_keys": 4, "hot_frac": 0.8, "batch": 8,
        "entity_key": "user_id", "tenants": ["interactive"],
    }),
    "tenant_spray": (_synth_tenant_spray, {
        "duration_s": 20.0, "base_rps": 40.0,
    }),
}


def synthesize(
    scenario: str,
    directory: str | Path,
    *,
    endpoint: str = "synthetic",
    seed: int = 0,
    **params: Any,
) -> Path:
    """Write a ``scenario`` artifact into ``directory`` (created);
    returns the artifact path. ``params`` override the scenario's
    defaults (see :data:`SCENARIOS`); unknown params are rejected so a
    typo'd knob fails here, not as a silently-default workload."""
    if scenario not in SCENARIOS:
        raise ValueError(
            f"unknown scenario {scenario!r}; have {sorted(SCENARIOS)}")
    gen, defaults = SCENARIOS[scenario]
    unknown = set(params) - set(defaults)
    if unknown:
        raise ValueError(
            f"unknown {scenario} params {sorted(unknown)}; "
            f"knobs are {sorted(defaults)}")
    p = {**defaults, **params}
    rng = _rng(seed, scenario)
    rows = gen(rng, p)
    recorder = WorkloadRecorder(
        directory,
        meta={"scenario": scenario, "seed": seed, "params": p,
              "synthetic": True},
    )
    # Fixed synthetic epoch: the segment streams are byte-identical
    # for one (scenario, params, seed) triple (only the manifest's
    # created_wall stamp varies between runs).
    base_wall = 1_700_000_000.0
    for row in rows:
        recorder.record(
            surface="synthetic",
            endpoint=endpoint,
            tenant=row.get("tenant"),
            payload=row["payload"],
            instances=row.get("entities"),
            t_mono=row["t"],
            t_wall=base_wall + row["t"],
        )
    return recorder.stop()
