"""Trace-driven workload capture, replay, and scenario synthesis.

PR 10 made individual requests observable; this package makes the
*workload itself* — the arrival process, tenant mix, and prompt/entity
shapes — a first-class, replayable artifact (ROADMAP item 5). Three
pieces, one schema:

- :mod:`~hops_tpu.telemetry.workload.capture` — a
  :class:`WorkloadRecorder` tapped into the fleet router and every
  serving endpoint records, per request, the monotonic+wall arrival
  time, tenant, endpoint, payload (full body below a size cap,
  shape-summary above it), entity-ID keys, LM prompt lengths, and the
  outcome (status, latency, trace-id cross-link) into a versioned
  append-only JSONL segment stream with rotation and a
  checkpoint-style size+SHA-256 manifest. Armed via
  ``HOPS_TPU_WORKLOAD_CAPTURE=<dir>`` or
  ``POST /admin/capture/start``; status at ``GET /debug/workload``.
- :mod:`~hops_tpu.telemetry.workload.replay` — verifies and loads an
  artifact (bitrot refuses loudly), deterministically re-materializes
  capped payloads from a seed, and re-issues the stream open-loop
  against any live configuration at ``--replay-speed`` multiples,
  reporting recorded-vs-replayed status mix / throughput / latency and
  arrival-fidelity stats.
- :mod:`~hops_tpu.telemetry.workload.synthesize` — produces artifacts
  in the same schema for what capture can't see: diurnal ramps,
  post-rollout thundering herds, hot-key entity skew, and adversarial
  tenant spray — so chaos tests and benches consume captured and
  synthetic workloads through one code path (``bench.py --replay``).

Stdlib-only: the capture tap lives on serving-host and router hot
paths that must never import JAX. Disabled capture costs one module
global read (``capturing()``), bounded by ``bench.py
--capture-overhead`` and its test, the same contract tracing and
faultinject keep. See docs/operations.md "Workload capture & replay".
"""

from hops_tpu.telemetry.workload.capture import (  # noqa: F401
    SCHEMA,
    WorkloadRecorder,
    admin_action,
    capturing,
    crash_flush,
    record_request,
    start_capture,
    status,
    stop_capture,
)
from hops_tpu.telemetry.workload.replay import (  # noqa: F401
    ReplayReport,
    WorkloadCorruptError,
    issued_stream,
    load_artifact,
    materialize_body,
    materialize_payload,
    replay,
)
from hops_tpu.telemetry.workload.synthesize import (  # noqa: F401
    SCENARIOS,
    synthesize,
)
