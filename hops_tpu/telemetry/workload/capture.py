"""Workload capture: the request stream as a replayable artifact.

Every perf or chaos question used to be answered with synthetic
Poisson load because the real workload — who arrived when, with what —
evaporates after every run. The :class:`WorkloadRecorder` writes one
JSON line per request into an append-only segment stream:

- **Versioned schema** (:data:`SCHEMA`): each record carries ``v``, a
  process-monotonic ``seq``, ``t_mono``/``t_wall`` arrival stamps, the
  ``surface`` that saw it (``router`` | ``serving`` | ``synthetic``),
  ``endpoint``/``path``/``tenant``, the payload (full body when its
  JSON serialization fits ``payload_cap_bytes``, a shape summary
  above that — instance count plus per-instance shape/keys, enough
  for the replay engine's seeded re-materialization), ``entity_keys``
  (the entity-ID dicts of feature-join requests, kept verbatim — skew
  is the workload), ``prompt_lens``/``budgets`` for LM requests, and
  the outcome: ``status``, ``latency_ms``, ``trace_id`` cross-link.
- **Rotation + manifest**: segments rotate at ``segment_bytes``; each
  finalized segment's size and SHA-256 land in ``manifest.json``
  (atomic replace), the same integrity discipline as checkpoint
  manifests — replay refuses bitrot instead of replaying garbage.
- **Crash flush**: :func:`crash_flush` (chained into
  ``flight.install_crash_handler``) finalizes the open segment and
  manifest so a crashed run's traffic is replayable post-mortem.

Arming: ``HOPS_TPU_WORKLOAD_CAPTURE=<dir>`` at import (value ``1`` /
``true`` picks a pid-suffixed directory under ``$TMPDIR``), or
:func:`start_capture` / ``POST /admin/capture/start`` at runtime;
``POST /admin/capture/stop`` finalizes. Status (armed, segments,
requests, bytes, drops) is served at ``GET /debug/workload``.

The disabled path must cost nothing: hot call sites guard with
``if workload.capturing():`` — one module-global read — before
building any record (``bench.py --capture-overhead`` and its test
hold this line, the contract ``faultinject.fire`` and tracing keep).
Stdlib-only: this is imported by serving hosts and the fleet router.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import threading
import time
from pathlib import Path
from typing import Any

from hops_tpu.runtime import faultinject
from hops_tpu.runtime.logging import get_logger
from hops_tpu.telemetry.metrics import REGISTRY

log = get_logger(__name__)

#: Artifact schema identifier; bump the suffix on breaking changes.
SCHEMA = "hops-tpu-workload/1"
#: Per-record schema version (travels on every line).
RECORD_VERSION = 1

DEFAULT_SEGMENT_BYTES = 4 << 20  # 4 MiB per segment before rotation
DEFAULT_PAYLOAD_CAP = 4096  # full-body capture cap (serialized bytes)

_m_captured = REGISTRY.counter(
    "hops_tpu_workload_captured_requests_total",
    "Requests recorded into the active workload-capture artifact, per "
    "capture surface (router | serving | synthetic)",
    labels=("surface",),
)
_m_dropped = REGISTRY.counter(
    "hops_tpu_workload_capture_dropped_total",
    "Requests the workload recorder failed to record (capture must "
    "never fail the request it observes)",
)
_m_segments = REGISTRY.counter(
    "hops_tpu_workload_capture_segments_total",
    "Workload-capture segments finalized into the artifact manifest",
)
_m_active = REGISTRY.gauge(
    "hops_tpu_workload_capture_active",
    "1 while this process is capturing its request stream, else 0 "
    "(the fleet router scrapes this for per-replica capture status)",
)


def _summarize_instance(inst: Any) -> dict[str, Any]:
    """Shape summary of one instance — enough structure for the replay
    engine to re-materialize a same-shape payload from a seed."""
    if isinstance(inst, dict):
        return {"kind": "dict", "keys": sorted(str(k) for k in inst)}
    if isinstance(inst, (list, tuple)):
        shape: list[int] = []
        probe: Any = inst
        while isinstance(probe, (list, tuple)):
            shape.append(len(probe))
            probe = probe[0] if probe else None
        return {"kind": "list", "shape": shape}
    return {"kind": type(inst).__name__}


def summarize_payload(payload: Any, cap_bytes: int) -> tuple[Any, Any]:
    """``(payload, None)`` when the serialized body fits ``cap_bytes``,
    else ``(None, summary)`` — byte size, instance count, and the first
    instance's shape (homogeneous batches are the serving contract)."""
    try:
        serialized = json.dumps(payload, default=str)
    except (TypeError, ValueError):
        return None, {"kind": "unserializable"}
    if len(serialized) <= cap_bytes:
        return payload, None
    summary: dict[str, Any] = {"bytes": len(serialized)}
    instances = payload.get("instances") if isinstance(payload, dict) else None
    if isinstance(instances, list):
        summary["instances"] = len(instances)
        if instances:
            summary["instance"] = _summarize_instance(instances[0])
    return None, summary


class WorkloadRecorder:
    """Append-only JSONL segment stream with rotation and a
    size+SHA-256 manifest. Thread-safe; :meth:`record` never raises
    past its own drop counter (capture must not fail the request)."""

    def __init__(
        self,
        directory: str | Path,
        *,
        segment_bytes: int = DEFAULT_SEGMENT_BYTES,
        payload_cap_bytes: int = DEFAULT_PAYLOAD_CAP,
        meta: dict[str, Any] | None = None,
    ):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        # Refuse a directory that already holds an artifact: appending
        # would clobber the old manifest and merge two processes'
        # records — whose t_mono stamps come from UNRELATED monotonic
        # clocks, so the merged stream's inter-arrival gaps are garbage
        # (a replay could sleep for days on one). One capture, one dir.
        existing = sorted(
            p.name for p in self.directory.glob("segment_*.jsonl"))
        if (self.directory / "manifest.json").exists() or existing:
            raise FileExistsError(
                f"{self.directory} already holds a workload artifact "
                f"({existing[:3] or ['manifest.json']}...) — captures "
                "never append across runs (their monotonic clocks don't "
                "compose); pick a fresh directory"
            )
        self.segment_bytes = int(segment_bytes)
        self.payload_cap_bytes = int(payload_cap_bytes)
        self._lock = threading.Lock()
        # guarded by: self._lock
        self._seq = 0
        self._segment_index = 0  # guarded by: self._lock
        self._segment_requests = 0  # guarded by: self._lock
        self._segment_first_seq = 1  # guarded by: self._lock
        self._bytes_written = 0  # guarded by: self._lock
        self._total_requests = 0  # guarded by: self._lock
        self._closed = False  # guarded by: self._lock
        self._manifest: dict[str, Any] = {
            "schema": SCHEMA,
            "created_wall": time.time(),
            "meta": dict(meta or {}),
            "closed": False,
            "segments": [],
        }  # guarded by: self._lock
        # Unbuffered: a failed write surfaces at the write itself (never
        # at a later flush), so the accounted byte count is always an
        # exact on-disk prefix and _resync_locked can truncate to it.
        self._fh = open(self._segment_path(0), "ab", buffering=0)  # guarded by: self._lock
        #: Helper threads fsync-publishing rolled segments; stop() joins
        #: them so the closed manifest holds every segment.
        self._publishers: list[threading.Thread] = []  # guarded by: self._lock
        # Running digest of the open segment, updated per written line:
        # finalization is O(1) — no 4 MiB read-back + re-hash while
        # request threads queue on the recorder lock.
        self._segment_hash = hashlib.sha256()  # guarded by: self._lock
        self._write_manifest_locked()

    # -- paths / manifest (call with self._lock held) -------------------------

    def _segment_path(self, index: int) -> Path:
        return self.directory / f"segment_{index:06d}.jsonl"

    def _write_manifest_locked(self) -> None:  # guarded by: self._lock
        tmp = self.directory / f"manifest.json.tmp{os.getpid()}"
        tmp.write_text(json.dumps(self._manifest, indent=2))
        os.replace(tmp, self.directory / "manifest.json")

    def _detach_segment_locked(  # guarded by: self._lock
        self, open_next: bool = True
    ) -> dict[str, Any]:
        """Swap the full segment out of the recorder state and open its
        successor, so :meth:`_publish_segment` can fsync and manifest it
        WITHOUT the lock. The entry's accounting (bytes, hash, seq
        range) is final at detach time — nothing writes to a detached
        handle — only its durability is still pending."""
        path = self._segment_path(self._segment_index)
        seg = {
            "fh": self._fh,
            "path": path,
            "entry": {
                "file": path.name,
                "bytes": self._bytes_written,
                "sha256": self._segment_hash.hexdigest(),
                "requests": self._segment_requests,
                "first_seq": self._segment_first_seq,
                "last_seq": self._seq,
            },
        }
        if open_next:
            self._open_next_segment_locked()
        else:
            self._fh = None  # closed recorder: _closed gates every write
        return seg

    def _publish_segment(self, seg: dict[str, Any]) -> None:
        """Make a detached segment durable, then manifest it.

        The fsync runs OUTSIDE the recorder lock — request threads used
        to queue behind a disk flush on every hot-path segment roll
        (graftlint: blocking-under-lock). The manifest entry lands only
        after the bytes are durable, so a crash can never leave the
        manifest referencing an unsynced segment; entries are kept
        sorted by ``first_seq`` because publishes may complete out of
        detach order."""
        fh = seg["fh"]
        try:
            faultinject.fire("workload.publish")  # chaos: slow disk
            fh.flush()
            os.fsync(fh.fileno())
        finally:
            fh.close()
        if seg["entry"]["requests"] == 0:
            seg["path"].unlink(missing_ok=True)
            return
        with self._lock:
            segments = self._manifest["segments"]
            segments.append(seg["entry"])
            segments.sort(key=lambda s: s["first_seq"])
            self._write_manifest_locked()
        _m_segments.inc()

    def _resync_locked(self) -> None:  # guarded by: self._lock
        """Recover from a failed record write: a partially-flushed line
        would desynchronize the file from the running hash/byte
        counters, and the NEXT finalized manifest would then refuse its
        own segment at replay. Drop the Python buffer, truncate the
        file back to the accounted length, and reopen; if even that
        fails (the disk is gone), close the recorder — a capture that
        can't stay consistent must stop, not poison its manifest."""
        path = self._segment_path(self._segment_index)
        try:
            try:
                self._fh.close()  # close() may re-attempt the bad flush
            except OSError:
                pass
            os.truncate(path, self._bytes_written)
            self._fh = open(path, "ab", buffering=0)
        except OSError:
            self._closed = True
            log.warning("workload capture: could not resync %s after a "
                        "failed write; capture stopped", path)

    def _open_next_segment_locked(self) -> None:  # guarded by: self._lock
        self._segment_index += 1
        self._segment_requests = 0
        self._segment_first_seq = self._seq + 1
        self._bytes_written = 0
        self._segment_hash = hashlib.sha256()
        self._fh = open(self._segment_path(self._segment_index), "ab",
                        buffering=0)

    # -- the capture surface --------------------------------------------------

    def record(
        self,
        *,
        surface: str,
        endpoint: str,
        path: str | None = None,
        tenant: str | None = None,
        payload: Any = None,
        instances: Any = None,
        lm_mode: bool = False,
        status: int | None = None,
        latency_ms: float | None = None,
        trace_id: str | None = None,
        t_mono: float | None = None,
        t_wall: float | None = None,
        wire_format: str | None = None,
        payload_summary: Any = None,
    ) -> dict[str, Any] | None:
        """Append one request record; returns it, or None on a drop
        (counted on ``hops_tpu_workload_capture_dropped_total`` — by
        contract a capture failure must never fail the request).

        ``payload_summary`` short-circuits :func:`summarize_payload`:
        packed-wire call sites already hold a header-only shape summary
        (the tensor body itself never JSON-serializes), so they pass it
        explicitly along with ``wire_format="packed"`` — the replayer
        re-materializes a same-shape packed frame from it."""
        try:
            if payload_summary is not None:
                body, summary = None, payload_summary
            else:
                body, summary = summarize_payload(
                    payload, self.payload_cap_bytes)
            rec: dict[str, Any] = {
                "v": RECORD_VERSION,
                "t_mono": time.monotonic() if t_mono is None else t_mono,
                "t_wall": time.time() if t_wall is None else t_wall,
                "surface": surface,
                "endpoint": endpoint,
            }
            if path:
                rec["path"] = path
            if tenant is not None:
                rec["tenant"] = tenant
            if wire_format and wire_format != "json":
                rec["wire_format"] = wire_format
            if body is not None:
                rec["payload"] = body
            if summary is not None:
                rec["payload_summary"] = summary
            if body is None and isinstance(instances, list) and instances:
                # Only for CAPPED payloads — a kept body already holds
                # the instances verbatim, and duplicating them would
                # double every feature-join record. Entity-ID keys
                # travel verbatim past the cap: key skew IS the
                # workload the feature store benches replay against.
                # The exemption is itself size-bounded — a batch of
                # WIDE dicts (full feature rows, not entity IDs) must
                # not smuggle megabytes past payload_cap_bytes; over
                # the bound the shape summary (keys + count) is what
                # replay re-materializes from.
                if all(isinstance(i, dict) and "prompt" not in i
                       for i in instances):
                    serialized_keys = json.dumps(
                        instances, separators=(",", ":"), default=str)
                    if len(serialized_keys) <= 4 * self.payload_cap_bytes:
                        rec["entity_keys"] = instances
                if lm_mode:
                    rec["prompt_lens"] = [
                        len(i.get("prompt", [])) if isinstance(i, dict)
                        else len(i)
                        for i in instances
                    ]
                    rec["budgets"] = [
                        int(i.get("max_new_tokens", 32))
                        if isinstance(i, dict) else 32
                        for i in instances
                    ]
            if status is not None:
                rec["status"] = int(status)
            if latency_ms is not None:
                rec["latency_ms"] = round(float(latency_ms), 3)
            if trace_id:
                rec["trace_id"] = trace_id
            with self._lock:
                if self._closed:
                    return None
                # seq is assigned under the lock so segments hold
                # strictly increasing sequence ranges.
                self._seq += 1
                rec["seq"] = self._seq
                line = (json.dumps(rec, separators=(",", ":"), default=str)
                        + "\n").encode()
                try:
                    self._fh.write(line)
                except Exception:
                    # ENOSPC/EIO mid-flush: part of the line may be on
                    # disk while the counters say it isn't. Resync (or
                    # stop) before the drop counter takes it.
                    self._resync_locked()
                    raise
                self._segment_hash.update(line)
                self._bytes_written += len(line)
                self._segment_requests += 1
                self._total_requests += 1
                if self._bytes_written >= self.segment_bytes:
                    # Hot-path roll: detach under the lock, fsync +
                    # manifest on a helper thread — concurrent record()
                    # calls keep appending to the fresh segment instead
                    # of queueing behind the flush. stop() joins these.
                    seg = self._detach_segment_locked()
                    t = threading.Thread(
                        target=self._publish_segment, args=(seg,),
                        daemon=True, name="workload-capture-publish",
                    )
                    self._publishers = [
                        p for p in self._publishers if p.is_alive()
                    ]
                    self._publishers.append(t)
                    t.start()
            _m_captured.inc(surface=surface)
            return rec
        except Exception:  # graftlint: disable=swallowed-exception
            _m_dropped.inc()  # by contract: see docstring
            return None

    def rotate(self) -> None:
        """Finalize the open segment into the manifest and start a new
        one — the crash-flush path: after this the artifact on disk is
        complete and replayable even if the process dies mid-write.
        Synchronous (durable on return), but the fsync itself runs with
        the lock released so concurrent record() calls don't stall."""
        with self._lock:
            if self._closed:
                return
            seg = self._detach_segment_locked()
        self._publish_segment(seg)

    def stop(self) -> Path:
        """Finalize everything; the artifact directory is the result."""
        with self._lock:
            if self._closed:
                return self.directory
            self._closed = True
            seg = self._detach_segment_locked(open_next=False)
            self._segment_requests = 0
            self._bytes_written = 0
            pending = list(self._publishers)
        self._publish_segment(seg)
        for t in pending:
            t.join()  # every in-flight roll must land before "closed"
        with self._lock:
            self._manifest["closed"] = True
            self._write_manifest_locked()
        return self.directory

    def status(self) -> dict[str, Any]:
        with self._lock:
            return {
                "directory": str(self.directory),
                "schema": SCHEMA,
                "requests": self._total_requests,
                "segments_finalized": len(self._manifest["segments"]),
                "open_segment_requests": self._segment_requests,
                "open_segment_bytes": self._bytes_written,
                "segment_bytes": self.segment_bytes,
                "payload_cap_bytes": self.payload_cap_bytes,
                "closed": self._closed,
            }


# -- process-global capture ----------------------------------------------------

_arm_lock = threading.Lock()
#: The armed recorder; read WITHOUT the lock on the hot path (arming
#: and disarming swap the whole reference under _arm_lock).
_RECORDER: WorkloadRecorder | None = None


def capturing() -> bool:
    """One module-global read: the hot-path guard every call site
    checks before building a record."""
    return _RECORDER is not None


def start_capture(
    directory: str | Path | None = None,
    *,
    segment_bytes: int = DEFAULT_SEGMENT_BYTES,
    payload_cap_bytes: int = DEFAULT_PAYLOAD_CAP,
    meta: dict[str, Any] | None = None,
) -> dict[str, Any]:
    """Arm process-global capture into ``directory`` (default: a
    pid-suffixed dir under ``$TMPDIR``). Idempotent while already
    capturing (returns the live status). Returns the capture status."""
    global _RECORDER
    with _arm_lock:
        if _RECORDER is not None:
            return status()
        if directory is None:
            directory = (Path(tempfile.gettempdir())
                         / f"hops_tpu_workload_{os.getpid()}")
        _RECORDER = WorkloadRecorder(
            directory, segment_bytes=segment_bytes,
            payload_cap_bytes=payload_cap_bytes, meta=meta,
        )
        _m_active.set(1)
        log.info("workload capture armed into %s", directory)
    return status()


def stop_capture() -> dict[str, Any] | None:
    """Disarm and finalize; returns the final status (with the
    artifact directory), or None when nothing was capturing."""
    global _RECORDER
    with _arm_lock:
        rec = _RECORDER
        if rec is None:
            return None
        _RECORDER = None
        _m_active.set(0)
    rec.stop()
    final = rec.status()
    final["capturing"] = False
    log.info("workload capture finalized: %s (%d requests, %d segments)",
             final["directory"], final["requests"],
             final["segments_finalized"])
    return final


def record_request(**fields: Any) -> None:
    """Record one request onto the armed recorder; no-op when disarmed
    (call sites guard with :func:`capturing` first, so the disarmed
    path never builds the field dict)."""
    rec = _RECORDER
    if rec is None:
        return
    rec.record(**fields)


def status() -> dict[str, Any]:
    """The ``GET /debug/workload`` body."""
    rec = _RECORDER
    if rec is None:
        return {"capturing": False}
    return {"capturing": True, **rec.status()}


def crash_flush() -> Path | None:
    """Finalize the open segment + manifest of an active capture so a
    crashed run's traffic is replayable post-mortem (chained into
    ``flight.install_crash_handler``). Capture stays armed — the crash
    may be another thread's. Returns the artifact dir, or None.
    Never raises: this runs on the way DOWN."""
    try:
        rec = _RECORDER
        if rec is None:
            return None
        rec.rotate()
        return rec.directory
    except Exception:  # graftlint: disable=swallowed-exception
        # By contract: a crash-path flush failure must not replace the
        # original exception being reported.
        return None


def admin_action(path: str, payload: dict[str, Any] | None) -> tuple[int, dict[str, Any]]:
    """The ``POST /admin/capture/{start,stop}`` control plane, shared
    by every serving endpoint and the fleet router (each mounts it in
    its own ``do_POST``). Returns ``(status_code, body)``."""
    p = path.split("?", 1)[0].rstrip("/")
    payload = payload if isinstance(payload, dict) else {}
    if p == "/admin/capture/start":
        try:
            return 200, start_capture(
                payload.get("dir"),
                segment_bytes=int(
                    payload.get("segment_bytes", DEFAULT_SEGMENT_BYTES)),
                payload_cap_bytes=int(
                    payload.get("payload_cap_bytes", DEFAULT_PAYLOAD_CAP)),
                meta=payload.get("meta"),
            )
        except (OSError, ValueError, TypeError) as e:
            return 400, {"error": f"{type(e).__name__}: {e}"}
    if p == "/admin/capture/stop":
        final = stop_capture()
        return 200, final if final is not None else {"capturing": False}
    return 404, {"error": f"unknown admin path {path}"}


def _arm_from_env() -> None:
    value = os.environ.get("HOPS_TPU_WORKLOAD_CAPTURE", "")
    if not value or value in ("0", "false"):
        return
    directory = None if value in ("1", "true") else value
    try:
        start_capture(directory)
    except OSError as e:
        # Misconfigured env must not kill every importing process.
        log.warning("HOPS_TPU_WORKLOAD_CAPTURE=%s: capture not armed: %s",
                    value, e)


_arm_from_env()
