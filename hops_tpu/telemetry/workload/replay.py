"""Workload replay: re-issue a captured stream against any live config.

The artifact is the experiment: ``bench.py --replay <dir>`` re-runs
yesterday's traffic — or a synthesized scenario — against a single
server, a fleet, or an LM engine at ``--replay-speed`` multiples.
Three layers:

- :func:`load_artifact` — verify the manifest (every segment's size
  and SHA-256, the checkpoint-manifest discipline) and parse the
  records; bitrot raises :class:`WorkloadCorruptError` with the
  offending file instead of replaying garbage.
- :func:`issued_stream` — the deterministic half: for each record,
  the intended issue offset (recorded inter-arrivals compressed by
  ``speed``) and the materialized body — recorded payloads verbatim,
  capped payloads re-materialized from ``(seed, seq)`` via SHA-256
  (platform-stable, same artifact + same seed ⇒ byte-identical
  stream; the determinism test pins this).
- :func:`replay` — the open-loop driver: a pacer thread sleeps until
  each intended offset and hands the request to a worker pool (late
  completions never delay later arrivals — open loop is the point),
  then the report compares recorded vs replayed status mix,
  throughput, and latency percentiles, plus arrival fidelity:
  achieved vs intended inter-arrival error (p50 error as a fraction
  of the intended p50 gap — the <10%-at-1x acceptance bound).

Replayed per-tenant metrics flow through a ``tenant_label`` collapser
(the fleet router's ``limiter.label_for``) so replaying a
tenant-spray capture cannot mint unbounded metric children.
Stdlib-only, like the rest of the package.
"""

from __future__ import annotations

import concurrent.futures
import hashlib
import json
import random
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path
from typing import Any, Callable

from hops_tpu.runtime.logging import get_logger
from hops_tpu.telemetry.metrics import REGISTRY
from hops_tpu.telemetry.workload.capture import SCHEMA

log = get_logger(__name__)

_m_replayed = REGISTRY.counter(
    "hops_tpu_workload_replayed_requests_total",
    "Requests re-issued by the workload replay engine, per collapsed "
    "tenant label (explicitly configured tenants keep their own child; "
    "everyone else folds into `default` via limiter.label_for)",
    labels=("tenant",),
)


class WorkloadCorruptError(RuntimeError):
    """A workload artifact failed its manifest integrity check —
    refusing to replay it (the checkpoint-corruption contract)."""


def load_artifact(path: str | Path, *, verify: bool = True) -> dict[str, Any]:
    """Load ``{"manifest", "records"}`` from an artifact directory.

    ``verify=True`` (default) checks every manifested segment's byte
    size and SHA-256 before parsing — a flipped bit raises
    :class:`WorkloadCorruptError` naming the segment, never a silent
    half-replay. Records come back sorted by ``t_mono``.
    """
    path = Path(path)
    manifest_path = path / "manifest.json"
    if not manifest_path.exists():
        raise WorkloadCorruptError(
            f"workload artifact {path} has no manifest.json — not a "
            "capture/synthesis output (or its finalization never ran)"
        )
    try:
        manifest = json.loads(manifest_path.read_text())
    except ValueError as e:
        raise WorkloadCorruptError(
            f"workload artifact {path}: manifest.json is not valid JSON "
            f"({e}) — refusing to replay"
        ) from e
    if manifest.get("schema") != SCHEMA:
        raise WorkloadCorruptError(
            f"workload artifact {path}: schema "
            f"{manifest.get('schema')!r} != {SCHEMA!r} — wrong or "
            "future artifact version; re-capture with this build"
        )
    records: list[dict[str, Any]] = []
    for seg in manifest.get("segments", []):
        seg_path = path / seg["file"]
        try:
            data = seg_path.read_bytes()
        except OSError as e:
            raise WorkloadCorruptError(
                f"workload artifact {path}: manifested segment "
                f"{seg['file']} is unreadable ({e}) — refusing to "
                "replay a partial capture"
            ) from e
        if verify:
            if len(data) != seg["bytes"]:
                raise WorkloadCorruptError(
                    f"workload artifact {path}: segment {seg['file']} is "
                    f"{len(data)} bytes, manifest says {seg['bytes']} — "
                    "truncated or appended-to after finalization; "
                    "refusing to replay (re-capture, or drop the "
                    "segment from manifest.json to accept the loss)"
                )
            digest = hashlib.sha256(data).hexdigest()
            if digest != seg["sha256"]:
                raise WorkloadCorruptError(
                    f"workload artifact {path}: segment {seg['file']} "
                    f"fails its SHA-256 check (bitrot) — refusing to "
                    "replay (re-capture, or drop the segment from "
                    "manifest.json to accept the loss)"
                )
        for line in data.splitlines():
            if not line.strip():
                continue
            try:
                records.append(json.loads(line))
            except ValueError as e:
                if verify:
                    # The checksum passed but a line won't parse: the
                    # manifest itself lied (or the writer was broken).
                    raise WorkloadCorruptError(
                        f"workload artifact {path}: segment {seg['file']} "
                        f"holds an unparsable record ({e}) despite a "
                        "passing checksum — refusing to replay"
                    ) from e
                log.warning("workload artifact %s: skipping unparsable "
                            "record in %s (verify=False)", path, seg["file"])
    records.sort(key=lambda r: (r.get("t_mono", 0.0), r.get("seq", 0)))
    return {"manifest": manifest, "records": records}


# -- deterministic re-materialization ------------------------------------------


def _rng_for(seed: int, seq: int) -> random.Random:
    # SHA-256, not hash(): str-hash is salted per process on 3.3+ and
    # tuple seeds drifted across versions — the faultinject lesson.
    digest = hashlib.sha256(f"workload:{seed}:{seq}".encode()).digest()
    return random.Random(int.from_bytes(digest[:8], "big"))


def materialize_payload(rec: dict[str, Any], seed: int) -> dict[str, Any]:
    """The request body to issue for ``rec``: the recorded payload
    verbatim when capture kept it, else a deterministic same-shape
    re-materialization from ``(seed, rec.seq)``."""
    if rec.get("payload") is not None:
        return rec["payload"]
    rng = _rng_for(seed, int(rec.get("seq", 0)))
    if rec.get("prompt_lens"):
        # LM request: regenerate token ids at the recorded lengths and
        # decode budgets (greedy + seed 0 keeps the replay itself
        # deterministic on the serving side).
        instances = [
            {"prompt": [rng.randrange(256) for _ in range(n)],
             "max_new_tokens": budget, "seed": 0}
            for n, budget in zip(
                rec["prompt_lens"],
                rec.get("budgets") or [32] * len(rec["prompt_lens"]))
        ]
        return {"instances": instances}
    if rec.get("entity_keys"):
        # Feature-join request: the entity-ID dicts were captured
        # verbatim (skew is the workload) — reuse them.
        return {"instances": rec["entity_keys"]}
    summary = rec.get("payload_summary") or {}
    n = int(summary.get("instances", 1))
    inst = summary.get("instance") or {}
    if inst.get("kind") == "list" and inst.get("shape"):
        # The summary's shape is ONE instance's shape (homogeneous
        # batches are the serving contract) — rebuild n of it.
        def build(shape: list[int]) -> Any:
            if len(shape) == 1:
                return [round(rng.uniform(-1.0, 1.0), 6)
                        for _ in range(shape[0])]
            return [build(shape[1:]) for _ in range(shape[0])]

        return {"instances": [build(list(inst["shape"]))
                              for _ in range(n)]}
    if inst.get("kind") == "dict" and inst.get("keys"):
        return {"instances": [
            {k: rng.randrange(1 << 16) for k in inst["keys"]}
            for _ in range(n)
        ]}
    return {"instances": [[round(rng.uniform(-1.0, 1.0), 6)]
                          for _ in range(n)]}


def materialize_body(rec: dict[str, Any],
                     seed: int) -> tuple[bytes, dict[str, str]]:
    """``(body_bytes, headers)`` for one record — the wire-level twin of
    :func:`materialize_payload`.

    Records captured off the packed wire (``wire_format: "packed"``)
    re-encode as a packed columnar frame in the summary's recorded
    dtype, with the matching ``Content-Type`` — a packed-body capture
    replays as packed traffic, not as a JSON approximation of it.
    Everything else serializes to canonical JSON. Deterministic: same
    record + same seed ⇒ identical bytes (the determinism test pins
    this)."""
    payload = materialize_payload(rec, seed)
    if rec.get("wire_format") == "packed":
        # Lazy import: wirecodec pulls numpy, which replay's jax-free
        # consumers only need when a packed record is actually present.
        import numpy as np

        from hops_tpu.runtime import wirecodec

        summary = rec.get("payload_summary") or {}
        try:
            arr = np.asarray(payload.get("instances"),
                             dtype=np.dtype(summary.get("dtype", "<f4")))
            frame = wirecodec.encode_frame([("instances", arr)])
        except (wirecodec.WireCodecError, TypeError, ValueError) as e:
            log.warning("workload replay: packed record seq=%s did not "
                        "re-encode (%s); issuing JSON instead",
                        rec.get("seq"), e)
        else:
            return frame, {"Content-Type": wirecodec.MEDIA_TYPE,
                           "Accept": wirecodec.MEDIA_TYPE}
    body = json.dumps(payload, separators=(",", ":"),
                      sort_keys=True).encode()
    return body, {"Content-Type": "application/json"}


def issued_stream(
    records: list[dict[str, Any]], *, seed: int = 0, speed: float = 1.0,
) -> list[dict[str, Any]]:
    """The deterministic issue plan: per record, the intended offset
    from replay start (recorded inter-arrivals divided by ``speed``),
    the serialized body, and the headers. Same records + same seed ⇒
    byte-identical plan (the determinism test pins this)."""
    if speed <= 0:
        raise ValueError(f"replay speed must be > 0, got {speed}")
    if not records:
        return []
    t0 = records[0].get("t_mono", 0.0)
    plan = []
    for rec in records:
        body, headers = materialize_body(rec, seed)
        if rec.get("tenant"):
            headers["X-Tenant"] = str(rec["tenant"])
        plan.append({
            "seq": rec.get("seq"),
            "offset_s": max(0.0, (rec.get("t_mono", t0) - t0)) / speed,
            "endpoint": rec.get("endpoint"),
            "tenant": rec.get("tenant"),
            "body": body,
            "headers": headers,
        })
    return plan


# -- the open-loop driver ------------------------------------------------------


def _http_target(base_url: str, timeout_s: float) -> Callable[..., int]:
    url = base_url.rstrip("/")
    if not url.endswith("/predict"):
        url = url + "/predict"

    def send(item: dict[str, Any]) -> int:
        req = urllib.request.Request(
            url, data=item["body"], headers=item["headers"])
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                resp.read()
                return resp.status
        except urllib.error.HTTPError as e:
            e.read()
            return e.code

    return send


def _percentile(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    return ordered[min(len(ordered) - 1, int(len(ordered) * q))]


class ReplayReport(dict):
    """The replay result: a plain dict (JSON-able as-is) with the
    recorded-vs-replayed comparison and arrival-fidelity stats."""


def _stream_stats(statuses: list[int], latencies_ms: list[float],
                  duration_s: float) -> dict[str, Any]:
    mix: dict[str, int] = {}
    for s in statuses:
        mix[str(s)] = mix.get(str(s), 0) + 1
    out: dict[str, Any] = {
        "requests": len(statuses),
        "status_mix": dict(sorted(mix.items())),
        "duration_s": round(duration_s, 4),
        "rps": round(len(statuses) / duration_s, 2) if duration_s > 0 else 0.0,
    }
    if latencies_ms:
        out["latency_p50_ms"] = round(_percentile(latencies_ms, 0.50), 3)
        out["latency_p99_ms"] = round(_percentile(latencies_ms, 0.99), 3)
    return out


def recorded_stats(records: list[dict[str, Any]]) -> dict[str, Any] | None:
    """The recorded side of the comparison — None for synthetic
    artifacts that carry no outcomes."""
    statuses = [r["status"] for r in records if r.get("status") is not None]
    if not statuses:
        return None
    latencies = [float(r["latency_ms"]) for r in records
                 if r.get("latency_ms") is not None]
    monos = [r["t_mono"] for r in records if "t_mono" in r]
    duration = (max(monos) - min(monos)) if len(monos) > 1 else 0.0
    return _stream_stats(statuses, latencies, max(duration, 1e-9))


def replay(
    records: list[dict[str, Any]],
    target: str | Callable[[dict[str, Any]], int],
    *,
    speed: float = 1.0,
    seed: int = 0,
    max_workers: int | None = None,
    request_timeout_s: float = 30.0,
    tenant_label: Callable[[str], str] | None = None,
) -> ReplayReport:
    """Open-loop replay of ``records`` against ``target`` (a base URL
    POSTed at ``/predict``, or a callable ``send(item) -> status``).

    The pacer holds the intended schedule regardless of response
    latency (slow responses consume pool workers, never delay
    arrivals); per-request results land in the report's
    ``replayed``/``arrival`` sections next to the ``recorded``
    baseline. ``max_workers`` defaults to the plan size (capped at
    512): a 32-thread default would quietly re-serialize anything past
    32 in flight and a thundering-herd burst would never land as one —
    an exhausted pool is exactly the open-loop violation this knob
    exists to avoid (the per-request ``achieved`` stamps record any
    residual slip either way). ``tenant_label`` collapses the
    per-tenant replay counter exactly like the router's rate-limit
    labels — pass ``router.limiter.label_for`` when replaying into a
    fleet."""
    plan = issued_stream(records, seed=seed, speed=speed)
    send = target if callable(target) else _http_target(
        target, request_timeout_s)
    if max_workers is None:
        max_workers = min(512, max(32, len(plan)))
    elif len(plan) > max_workers:
        log.warning(
            "workload replay: %d requests over a %d-worker pool — "
            "bursts wider than the pool will issue late (open-loop "
            "fidelity degrades; see the arrival error stats)",
            len(plan), max_workers)
    label = tenant_label if tenant_label is not None else (
        lambda tenant: "default")

    results: list[dict[str, Any]] = []
    results_lock = threading.Lock()

    def issue(item: dict[str, Any], intended: float, t0: float) -> None:
        achieved = time.monotonic() - t0
        t_req = time.perf_counter()
        try:
            status = send(item)
            error = None
        except Exception as e:  # noqa: BLE001 — a replay error is a data point
            status, error = -1, f"{type(e).__name__}: {e}"
        latency_ms = (time.perf_counter() - t_req) * 1e3
        _m_replayed.inc(tenant=label(item.get("tenant") or ""))
        row = {"seq": item["seq"], "intended_s": intended,
               "achieved_s": achieved, "status": status,
               "latency_ms": latency_ms}
        if error is not None:
            row["error"] = error
        with results_lock:
            results.append(row)

    pool = concurrent.futures.ThreadPoolExecutor(
        max_workers=max_workers, thread_name_prefix="workload-replay")
    t0 = time.monotonic()
    try:
        for item in plan:
            delay = item["offset_s"] - (time.monotonic() - t0)
            if delay > 0:
                time.sleep(delay)
            pool.submit(issue, item, item["offset_s"], t0)
    finally:
        pool.shutdown(wait=True)
    wall = time.monotonic() - t0

    results.sort(key=lambda r: r["intended_s"])
    statuses = [r["status"] for r in results]
    latencies = [r["latency_ms"] for r in results if r["status"] >= 0]
    intended_gaps = [
        b["intended_s"] - a["intended_s"]
        for a, b in zip(results, results[1:])
    ]
    achieved_gaps = [
        b["achieved_s"] - a["achieved_s"]
        for a, b in zip(results, results[1:])
    ]
    gap_errors = [abs(a - i) for a, i in zip(achieved_gaps, intended_gaps)]
    p50_gap = _percentile(intended_gaps, 0.50)
    p50_err = _percentile(gap_errors, 0.50)
    report = ReplayReport(
        speed=speed,
        seed=seed,
        replayed=_stream_stats(statuses, latencies, max(wall, 1e-9)),
        arrival={
            "intended_interarrival_p50_ms": round(p50_gap * 1e3, 3),
            "achieved_error_p50_ms": round(p50_err * 1e3, 3),
            "achieved_error_p95_ms": round(
                _percentile(gap_errors, 0.95) * 1e3, 3),
            # The acceptance bound: p50 |achieved - intended| gap error
            # as a fraction of the intended p50 gap (< 0.10 at 1x).
            "p50_error_frac": round(p50_err / p50_gap, 4) if p50_gap > 0
            else 0.0,
        },
        errors=sum(1 for s in statuses if s < 0),
    )
    recorded = recorded_stats(records)
    if recorded is not None:
        report["recorded"] = recorded
    return report
