"""``hops_tpu.telemetry`` — metrics registry, export, and span timers.

The observability subsystem (SURVEY.md §5: the reference shipped
per-serving Kafka inference logs to ELK and scraped Spark executor
metrics; MLPerf-scale TPU work treats step-time/throughput telemetry as
a first-class subsystem):

- :mod:`~hops_tpu.telemetry.metrics` — thread-safe, label-aware
  ``Counter`` / ``Gauge`` / ``Histogram`` in a process-global
  ``REGISTRY``, host-tagged like ``runtime/logging.py``.
- :mod:`~hops_tpu.telemetry.export` — Prometheus text exposition
  (``GET /metrics`` standalone or mounted on a serving's port), JSON
  snapshots, and periodic export onto ``messaging.pubsub``.
- :mod:`~hops_tpu.telemetry.spans` — ``with span(...)`` / ``@timed``
  block timers feeding histograms, nesting inside
  ``diagnostics.trace`` profiler captures; ``StepTimer`` for training
  loops.
- :mod:`~hops_tpu.telemetry.tracing` — W3C-style distributed request
  tracing (``traceparent`` in/out, contextvar-carried spans, a
  sampling ``Tracer`` with a bounded ring) served at
  ``GET /debug/traces``; ``span(...)`` joins the active trace so the
  metrics and tracing vocabularies stay one thing.
- :mod:`~hops_tpu.telemetry.workload` — trace-driven workload capture
  (the request stream as a versioned, manifest-verified JSONL
  artifact), deterministic open-loop replay at adjustable speed, and
  a scenario synthesizer (diurnal / herd / hot-key / tenant-spray)
  in the same schema; status at ``GET /debug/workload``, replayed by
  ``bench.py --replay``.

Instrumented out of the box: serving request/error/latency per model,
LM engine TTFT / tokens / slot occupancy / prefix-cache hits /
dispatches, dynamic-batcher queue depth and fill, experiment step
time, search trial lifecycle, feature-store feed throughput, and the
preemption heartbeat gauge the Watchdog can read.
"""

from hops_tpu.telemetry.metrics import (  # noqa: F401
    DEFAULT_BUCKETS,
    RATIO_BUCKETS,
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    Registry,
    hosttag,
)
from hops_tpu.telemetry.export import (  # noqa: F401
    MetricsServer,
    PubsubExporter,
    render_prometheus,
    snapshot,
    start_http_server,
)
from hops_tpu.telemetry.spans import (  # noqa: F401
    HEARTBEAT_GAUGE,
    StepTimer,
    span,
    timed,
)
from hops_tpu.telemetry import tracing  # noqa: F401
from hops_tpu.telemetry import workload  # noqa: F401
from hops_tpu.telemetry.tracing import (  # noqa: F401
    TRACER,
    Span,
    TraceContext,
    Tracer,
    child_span,
    current_context,
    current_trace_id,
    parse_traceparent,
    start_trace,
)
