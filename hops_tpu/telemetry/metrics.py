"""Thread-safe, label-aware metrics registry.

The reference platform surrounded every workload with observability —
per-serving Kafka inference logs shipped to ELK, Spark executor metrics,
TensorBoard profiling (SURVEY.md §5) — but until now this reproduction
had only structured logging and hang detection. This module is the
counters/gauges/histograms layer underneath all of it: a process-local
:class:`Registry` of named metrics, each optionally labelled, safe to
update from any thread (serving handler threads, the LM engine driver,
search executors) and cheap enough for hot paths (one lock acquire + a
dict lookup per update; bind with :meth:`_Metric.labels` to skip the
lookup).

Stdlib-only by design: importing this module must never pull in JAX —
metrics are updated from processes that may not own the accelerator
(serving hosts, job children). The host tag reuses the convention from
``runtime/logging.py``: ``h<process_index>`` once the JAX backend is up,
``h?`` before/without it, computed lazily at export time only.

Naming scheme (see docs/operations.md "Telemetry & metrics"):
``hops_tpu_<subsystem>_<what>[_<unit>]`` with ``_total`` for counters
and ``_seconds`` for latency histograms — the Prometheus conventions,
so ``export.render_prometheus`` is a straight transcription.
"""

from __future__ import annotations

import bisect
import math
import threading
import time
from typing import Any, Iterable

#: Latency buckets (seconds): sub-ms dispatch overheads up to the
#: minute-scale experiment steps — shared default for every `_seconds`
#: histogram so dashboards line up across subsystems.
DEFAULT_BUCKETS: tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

#: Buckets for ratios in [0, 1] (batch fill, occupancy).
RATIO_BUCKETS: tuple[float, ...] = (
    0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0,
)


def hosttag() -> str:
    """``h<process_index>`` — the per-host prefix from
    ``runtime/logging.py``. Tags with the real index ONLY if the JAX
    backend is already initialized: touching ``jax.process_index()``
    here would otherwise initialize it as a side effect of a metrics
    scrape, which blocks for minutes in processes that can't reach the
    accelerator."""
    try:
        from jax._src import xla_bridge

        if xla_bridge.backends_are_initialized():
            import jax

            return f"h{jax.process_index()}"
    except Exception:  # graftlint: disable=swallowed-exception
        pass  # by contract: a metrics scrape must NEVER raise or init jax
    return "h?"


class _Metric:
    """Base: a named family of (label-values -> value) children."""

    type: str = "untyped"

    def __init__(self, name: str, help: str, label_names: tuple[str, ...]):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], Any] = {}  # guarded by: self._lock

    def _key(self, labels: dict[str, Any]) -> tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name} declared labels {self.label_names}, "
                f"got {tuple(labels)}"
            )
        return tuple(str(labels[k]) for k in self.label_names)

    def labels(self, **labels: Any) -> Any:
        """Bind a child for repeated hot-path updates (one dict lookup
        amortized away)."""
        key = self._key(labels)
        with self._lock:
            return self._child(key)

    def _child(self, key: tuple[str, ...]) -> Any:  # guarded by: self._lock
        raise NotImplementedError

    def samples(self) -> list[tuple[str, dict[str, str], float]]:
        """``(name_suffix, labels, value)`` rows for the exporter."""
        raise NotImplementedError


class _CounterChild:
    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self.value += amount


class Counter(_Metric):
    """Monotonically increasing count (requests, tokens, trials)."""

    type = "counter"

    def _child(self, key: tuple[str, ...]) -> _CounterChild:  # guarded by: self._lock
        child = self._children.get(key)
        if child is None:
            child = self._children[key] = _CounterChild(self._lock)
        return child

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        self.labels(**labels).inc(amount)

    def value(self, **labels: Any) -> float:
        return self.labels(**labels).value

    def samples(self) -> list[tuple[str, dict[str, str], float]]:
        with self._lock:
            items = list(self._children.items())
        return [
            ("", dict(zip(self.label_names, key)), child.value)
            for key, child in items
        ]


class _GaugeChild:
    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class Gauge(_Metric):
    """Point-in-time value (queue depth, occupancy, heartbeat time)."""

    type = "gauge"

    def _child(self, key: tuple[str, ...]) -> _GaugeChild:  # guarded by: self._lock
        child = self._children.get(key)
        if child is None:
            child = self._children[key] = _GaugeChild(self._lock)
        return child

    def set(self, value: float, **labels: Any) -> None:
        self.labels(**labels).set(value)

    def set_to_current_time(self, **labels: Any) -> None:
        self.labels(**labels).set(time.time())

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        self.labels(**labels).inc(amount)

    def dec(self, amount: float = 1.0, **labels: Any) -> None:
        self.labels(**labels).dec(amount)

    def value(self, **labels: Any) -> float:
        return self.labels(**labels).value

    def samples(self) -> list[tuple[str, dict[str, str], float]]:
        with self._lock:
            items = list(self._children.items())
        return [
            ("", dict(zip(self.label_names, key)), child.value)
            for key, child in items
        ]


class _HistogramChild:
    __slots__ = ("_lock", "bounds", "counts", "sum", "count", "exemplars")

    def __init__(self, lock: threading.Lock, bounds: tuple[float, ...]):
        self._lock = lock
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # last = +Inf overflow
        self.sum = 0.0
        self.count = 0
        # bucket index -> (trace_id, value, unix time): the newest
        # traced observation per bucket, so a p99 bucket on a dashboard
        # links to a concrete trace in the ring (OpenMetrics-style
        # exemplars; export renders them behind a flag).
        # guarded by: self._lock
        self.exemplars: dict[int, tuple[str, float, float]] | None = None

    def snapshot(self) -> tuple[tuple[float, ...], list[int], int]:
        """Consistent ``(bounds, per-bucket counts, total count)`` view
        — quantile estimators (the fleet router's windowed p99) diff
        two snapshots instead of reaching into the fields unlocked."""
        with self._lock:
            return self.bounds, list(self.counts), self.count

    def observe(self, value: float, exemplar: str | None = None) -> None:
        value = float(value)
        # NaN compares false against every bound (bisect would file it
        # under the SMALLEST bucket); Prometheus clients count it only
        # in +Inf/_count, so route it to the overflow slot.
        if math.isnan(value):
            i = len(self.bounds)
        else:
            i = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self.counts[i] += 1
            self.sum += value
            self.count += 1
            if exemplar is not None:
                if self.exemplars is None:
                    self.exemplars = {}
                self.exemplars[i] = (exemplar, value, time.time())


class Histogram(_Metric):
    """Distribution (latencies, fill ratios) with cumulative buckets in
    the Prometheus exposition."""

    type = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        label_names: tuple[str, ...],
        buckets: Iterable[float] = DEFAULT_BUCKETS,
    ):
        super().__init__(name, help, label_names)
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        if any(math.isinf(b) for b in bounds):
            bounds = tuple(b for b in bounds if not math.isinf(b))
        self.buckets = bounds

    def _child(self, key: tuple[str, ...]) -> _HistogramChild:  # guarded by: self._lock
        child = self._children.get(key)
        if child is None:
            child = self._children[key] = _HistogramChild(self._lock, self.buckets)
        return child

    def observe(self, value: float, exemplar: str | None = None,
                **labels: Any) -> None:
        self.labels(**labels).observe(value, exemplar=exemplar)

    def exemplars(self) -> dict[tuple[tuple[str, ...], str], tuple[str, float, float]]:
        """``(child_key, le) -> (trace_id, value, time)`` — the newest
        traced observation per bucket, keyed the way the exporter
        reconstructs bucket rows."""
        with self._lock:
            items = [
                (key, dict(child.exemplars))
                for key, child in self._children.items()
                if child.exemplars
            ]
        out: dict[tuple[tuple[str, ...], str], tuple[str, float, float]] = {}
        for key, ex in items:
            for i, row in ex.items():
                le = _fmt(self.buckets[i]) if i < len(self.buckets) else "+Inf"
                out[(key, le)] = row
        return out

    def samples(self) -> list[tuple[str, dict[str, str], float]]:
        with self._lock:
            items = [
                (key, list(child.counts), child.sum, child.count)
                for key, child in self._children.items()
            ]
        rows: list[tuple[str, dict[str, str], float]] = []
        for key, counts, total, count in items:
            base = dict(zip(self.label_names, key))
            cum = 0
            for bound, c in zip(self.buckets, counts):
                cum += c
                rows.append(("_bucket", {**base, "le": _fmt(bound)}, float(cum)))
            rows.append(("_bucket", {**base, "le": "+Inf"}, float(count)))
            rows.append(("_sum", base, total))
            rows.append(("_count", base, float(count)))
        return rows


def _fmt(bound: float) -> str:
    """Prometheus-style bucket bound: integral bounds render bare."""
    return str(int(bound)) if bound == int(bound) else repr(bound)


class Registry:
    """Named metrics, get-or-create. One process-global :data:`REGISTRY`
    serves every subsystem; tests may build private ones. Get-or-create
    is what lets two modules share a well-known metric (the heartbeat
    gauge the Watchdog reads) without import-order coupling — but a
    name re-declared with a different type or label set is a bug and
    raises."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}  # guarded by: self._lock

    def _get_or_create(self, cls, name: str, help: str,
                       labels: Iterable[str], **kwargs: Any) -> Any:
        label_names = tuple(labels)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls) or existing.label_names != label_names:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.type}{existing.label_names}, "
                        f"conflicting re-declaration"
                    )
                if kwargs.get("buckets") is not None:
                    # Same normalization the Histogram ctor applies —
                    # silently handing back differently-bucketed series
                    # would corrupt the second declarer's quantiles.
                    # buckets=None (a read-back, not a declaration)
                    # skips the check: readers must not have to restate
                    # the declarer's buckets.
                    wanted = tuple(sorted(
                        float(b) for b in kwargs["buckets"] if not math.isinf(b)
                    ))
                    if wanted != existing.buckets:
                        raise ValueError(
                            f"histogram {name!r} already registered with "
                            f"buckets {existing.buckets}, conflicting "
                            f"re-declaration with {wanted}"
                        )
                return existing
            if "buckets" in kwargs and kwargs["buckets"] is None:
                kwargs["buckets"] = DEFAULT_BUCKETS
            metric = cls(name, help, label_names, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "",
                labels: Iterable[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Iterable[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Iterable[str] = (),
                  buckets: Iterable[float] | None = None) -> Histogram:
        """``buckets=None`` means "declarer's default" on first
        registration (:data:`DEFAULT_BUCKETS`) and "whatever was
        declared" on read-back — explicit buckets are a declaration and
        must match any existing one."""
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def get(self, name: str) -> _Metric | None:
        with self._lock:
            return self._metrics.get(name)

    def collect(self) -> list[_Metric]:
        """Stable-order snapshot of the registered metric families."""
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def reset(self) -> None:
        """Drop every metric — test isolation only: modules keep direct
        references to metric objects they created, so resetting a live
        process orphans (not re-links) those references."""
        with self._lock:
            self._metrics.clear()


#: The process-global registry every subsystem instruments into.
REGISTRY = Registry()
