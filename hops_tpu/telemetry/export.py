"""Metric export: Prometheus text exposition, JSON snapshots, pubsub.

Three consumers, one registry (``metrics.REGISTRY``):

- **Prometheus scrape** — :func:`render_prometheus` produces text
  exposition format 0.0.4; :func:`start_http_server` serves it at
  ``GET /metrics`` from a standalone daemon thread, and
  ``modelrepo/serving.py`` mounts the same rendering on every started
  serving's own port (scrape the model server directly, the way the
  reference's serving containers were scraped).
- **JSON snapshot** — :func:`snapshot` for dashboards/tests; also
  served at ``GET /metrics.json``.
- **Pubsub tail** — :class:`PubsubExporter` periodically appends
  snapshots onto a ``messaging.pubsub`` topic: the TPU-native stand-in
  for the reference's Kafka→ELK metrics pipeline (SURVEY.md §5) —
  consumers replay/tail it exactly like the inference logs.

Every exported series carries a ``host`` label (the
``runtime/logging.py`` hosttag convention) so multi-host scrapes and a
shared pubsub topic stay disambiguated.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from http.server import BaseHTTPRequestHandler
from typing import Any

from hops_tpu.telemetry import metrics as _metrics
from hops_tpu.telemetry.metrics import REGISTRY, Histogram, Registry

#: Render histogram exemplars (`# {trace_id="..."} value ts` appended
#: to bucket rows) in the Prometheus exposition. Off by default: the
#: `# {...}` suffix is OpenMetrics syntax and some 0.0.4-only scrapers
#: choke on it — flip via env or pass ``exemplars=`` explicitly.
EXEMPLARS_ENABLED = os.environ.get(
    "HOPS_TPU_METRIC_EXEMPLARS", "0") not in ("0", "false", "")


def _escape(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')
    )


def _format_value(v: float) -> str:
    # Non-finite values use the exposition-format spellings; int()
    # comparison on them would raise and permanently 500 the scrape
    # (one diverged-loss observe must not kill /metrics).
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def render_prometheus(registry: Registry = REGISTRY,
                      exemplars: bool | None = None) -> str:
    """Text exposition format 0.0.4 — what ``GET /metrics`` returns.

    ``exemplars=True`` (default: :data:`EXEMPLARS_ENABLED`) appends
    OpenMetrics-style exemplars to histogram bucket rows —
    ``# {trace_id="..."} value timestamp`` — linking a latency bucket
    to a concrete trace retrievable from ``GET /debug/traces/<id>``.
    """
    if exemplars is None:
        exemplars = EXEMPLARS_ENABLED
    host = _metrics.hosttag()
    out: list[str] = []
    for metric in registry.collect():
        if metric.help:
            out.append(f"# HELP {metric.name} {_escape(metric.help)}")
        out.append(f"# TYPE {metric.name} {metric.type}")
        ex_rows = (
            metric.exemplars()
            if exemplars and isinstance(metric, Histogram) else {}
        )
        for suffix, labels, value in metric.samples():
            labeled = {"host": host, **labels}
            body = ",".join(
                f'{k}="{_escape(str(v))}"' for k, v in labeled.items()
            )
            line = f"{metric.name}{suffix}{{{body}}} {_format_value(value)}"
            if ex_rows and suffix == "_bucket":
                key = tuple(
                    str(labels[k]) for k in metric.label_names
                    if k in labels
                )
                ex = ex_rows.get((key, labels.get("le", "")))
                if ex is not None:
                    tid, ex_value, ex_time = ex
                    line += (
                        f' # {{trace_id="{_escape(tid)}"}} '
                        f"{_format_value(ex_value)} {ex_time:.3f}"
                    )
            out.append(line)
    return "\n".join(out) + "\n"


def snapshot(registry: Registry = REGISTRY,
             families: "set[str] | None" = None) -> dict[str, Any]:
    """JSON-able point-in-time dump of every metric family.

    ``families`` restricts the dump to the named families — the hot
    scrape path: the fleet router polls every replica several times a
    second to read FOUR gauges, and rendering + parsing the full
    registry per poll was almost all of that cost."""
    out: dict[str, Any] = {}
    for metric in registry.collect():
        if families is not None and metric.name not in families:
            continue
        rows = [
            {"suffix": suffix, "labels": labels, "value": value}
            for suffix, labels, value in metric.samples()
        ]
        out[metric.name] = {
            "type": metric.type,
            "help": metric.help,
            "samples": rows,
        }
    return {"time": time.time(), "host": _metrics.hosttag(), "metrics": out}


def metrics_response(
    path_qs: str, registry: Registry = REGISTRY
) -> "tuple[int, dict[str, str], bytes] | None":
    """The pure half of :func:`handle_metrics_path`: given a request
    path (query string attached), return ``(status, headers, body)``
    for the metrics routes, or ``None`` when the path is not one —
    the shape the event-loop transport's ``route`` contract consumes
    directly (``runtime/httpserver.py``).

    ``GET /metrics.json?families=a,b`` serves only the named families
    (unknown names are simply absent) — the router's scrape asks for
    exactly the gauges it routes on instead of the whole registry."""
    path, _, query = path_qs.partition("?")
    path = path.rstrip("/")
    if path == "/metrics":
        data = render_prometheus(registry).encode()
        ctype = "text/plain; version=0.0.4; charset=utf-8"
    elif path == "/metrics.json":
        wanted = None
        if query:
            from urllib.parse import parse_qs

            raw = parse_qs(query).get("families", [])
            names = {n for part in raw for n in part.split(",") if n}
            wanted = names or None
        data = json.dumps(snapshot(registry, families=wanted)).encode()
        ctype = "application/json"
    else:
        return None
    return 200, {"Content-Type": ctype}, data


def handle_metrics_path(handler: BaseHTTPRequestHandler,
                        registry: Registry = REGISTRY) -> bool:
    """Serve ``GET /metrics`` / ``GET /metrics.json`` on an existing
    ``BaseHTTPRequestHandler`` — the stdlib-handler wrapper around
    :func:`metrics_response`, kept for any embedder still on the
    thread-per-connection transport. Returns True if the request path
    was a metrics route (and was answered)."""
    resp = metrics_response(handler.path, registry)
    if resp is None:
        return False
    status, headers, data = resp
    handler.send_response(status)
    for k, v in headers.items():
        handler.send_header(k, v)
    handler.send_header("Content-Length", str(len(data)))
    handler.end_headers()
    handler.wfile.write(data)
    return True


def debug_response(path_qs: str) -> "tuple[int, dict[str, str], bytes] | None":
    """The pure half of :func:`handle_debug_path`: ``(status, headers,
    body)`` for the debug surfaces, or ``None`` when the path is not
    one. Mounted beside :func:`metrics_response` on every serving,
    replica, and router port (and on :class:`MetricsServer`). Routes
    (docs/operations.md "Tracing & debugging"):

    - ``GET /debug/traces`` — newest-first trace summaries over this
      process's span ring; ``?limit=N`` caps the summary count and
      ``?since=<wall-time>`` drops traces that started before the
      stamp (malformed values degrade to the defaults, never a 500);
    - ``GET /debug/traces/<trace_id>`` — every recorded span of one
      trace (404 when the ring holds none);
    - ``GET /debug/flight`` — the flight recorder's event ring;
    - ``GET /debug/workload`` — workload-capture status (armed,
      artifact directory, segment/request/byte counts).
    """
    # Lazy: flight lives in runtime (which imports this package).
    from hops_tpu.runtime import flight as _flight
    from hops_tpu.telemetry import tracing as _tracing
    from hops_tpu.telemetry import workload as _workload

    path, _, query = path_qs.partition("?")
    path = path.rstrip("/")
    code = 200
    if path == "/debug/traces":
        from urllib.parse import parse_qs

        params = parse_qs(query)

        def qnum(key: str, cast, default):
            try:
                return cast(params[key][0])
            except (KeyError, IndexError, ValueError):
                return default

        limit = qnum("limit", int, 50)
        if limit < 0:
            # A negative slice would drop the NEWEST traces — the
            # opposite of any caller's intent; degrade like any other
            # malformed value.
            limit = 50
        since = qnum("since", float, None)
        body: dict[str, Any] = {
            "enabled": _tracing.enabled(),
            "sample_rate": _tracing.TRACER.sample_rate,
            "ring_size": _tracing.TRACER.ring_size,
            "traces": _tracing.TRACER.traces(limit=limit, since=since),
        }
    elif path.startswith("/debug/traces/"):
        trace_id = path[len("/debug/traces/"):]
        spans = _tracing.TRACER.get_trace(trace_id)
        if spans:
            body = {"trace_id": trace_id, "spans": spans}
        else:
            code, body = 404, {"error": f"no spans for trace {trace_id!r} "
                                        "in this process's ring"}
    elif path == "/debug/flight":
        body = _flight.FLIGHT.snapshot()
    elif path == "/debug/workload":
        body = _workload.status()
    else:
        return None
    data = json.dumps(body, default=str).encode()
    return code, {"Content-Type": "application/json"}, data


def handle_debug_path(handler: BaseHTTPRequestHandler) -> bool:
    """Serve the debug surfaces on an existing stdlib handler — the
    thread-per-connection wrapper around :func:`debug_response`.
    Returns True if the request path was a debug route (and answered).
    """
    resp = debug_response(handler.path)
    if resp is None:
        return False
    code, headers, data = resp
    handler.send_response(code)
    for k, v in headers.items():
        handler.send_header(k, v)
    handler.send_header("Content-Length", str(len(data)))
    handler.end_headers()
    handler.wfile.write(data)
    return True


class MetricsServer:
    """Standalone scrape endpoint serving ``/metrics`` (Prometheus
    text) and ``/metrics.json`` — plus the ``/debug/*`` surfaces — for
    processes that have no serving port of their own (training jobs,
    the search driver). Rides the shared event-loop transport
    (``runtime/httpserver.py``); scrapes are read-only and cheap, so a
    small worker pool is plenty."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry: Registry = REGISTRY):
        # Lazy: runtime/httpserver imports this package's metrics
        # module; importing it at export's module top would cycle.
        from hops_tpu.runtime.httpserver import HTTPServer

        registry_ = registry

        def route(method: str, path: str, headers: Any,
                  body: bytes) -> tuple[int, dict[str, str], bytes]:
            resp = metrics_response(path, registry_) or debug_response(path)
            if resp is None:
                return 404, {"Content-Type": "application/json"}, b"{}"
            return resp

        self._server = HTTPServer(
            route, bind=host, port=port, name="metrics", workers=2)

    @property
    def port(self) -> int:
        return self._server.port

    def stop(self) -> None:
        self._server.stop()

    def __enter__(self) -> "MetricsServer":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.stop()


def start_http_server(port: int = 0, registry: Registry = REGISTRY) -> MetricsServer:
    """Start a :class:`MetricsServer`; ``port=0`` picks a free one
    (read it back from ``.port``)."""
    return MetricsServer(port=port, registry=registry)


class PubsubExporter:
    """Periodic snapshot export onto a ``messaging.pubsub`` topic.

    The reference shipped per-serving metrics over Kafka into ELK;
    here every ``interval_s`` a :func:`snapshot` is appended to
    ``topic`` (default ``telemetry-metrics``), keyed by host tag —
    durable, replayable, shared-filesystem-wide. A final snapshot is
    flushed on :meth:`stop` so short-lived jobs still leave a record.
    """

    def __init__(self, topic: str = "telemetry-metrics",
                 interval_s: float = 10.0,
                 registry: Registry = REGISTRY):
        self.topic = topic
        self.interval_s = interval_s
        self._registry = registry
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._producer = None

    def _send(self) -> None:
        from hops_tpu.messaging import pubsub

        if self._producer is None:
            self._producer = pubsub.Producer(self.topic)
        self._producer.send(snapshot(self._registry), key=_metrics.hosttag())

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._send()
            except Exception:  # noqa: BLE001 — export must not kill the host
                from hops_tpu.runtime.logging import get_logger

                get_logger(__name__).exception("pubsub metrics export failed")

    def start(self) -> "PubsubExporter":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="hops-metrics-pubsub"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        try:
            self._send()  # final flush: short jobs still leave a record
        except Exception:  # noqa: BLE001 — flush is best-effort, but say so
            from hops_tpu.runtime.logging import get_logger

            get_logger(__name__).exception("final pubsub metrics flush failed")

    def __enter__(self) -> "PubsubExporter":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()
