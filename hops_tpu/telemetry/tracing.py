"""Distributed request tracing: W3C trace context over the serving stack.

PR 1's telemetry is aggregate-only — histograms say p99 rose, nothing
says *which* hop of *which* request spent the time. This module is the
causal thread: a request entering the fleet router starts (or, carrying
a ``traceparent`` header, extends) a **trace**; every hop — router
forward, replica handler, dynamic-batcher queue/compute, LM engine
dispatch, feature join — records a **span** with the trace id, its own
span id, and its parent's, so the whole path reassembles into one tree.

Design constraints, in order:

- **Disabled must cost nothing.** Every serving hot path calls into
  here unconditionally; with tracing off the entry points are one
  module-flag test (the ``bench.py --tracing-overhead`` tier and its
  test hold this line, the same contract ``faultinject.fire`` keeps).
- **Stdlib-only.** Spans are recorded from processes that must never
  touch JAX (serving hosts, the fleet router).
- **Bounded memory.** Finished spans land in a ring
  (:class:`Tracer`, default 512 spans); old traces fall off the back.
  ``GET /debug/traces`` (telemetry/export.py) serves the ring.

Context is carried on a :mod:`contextvars` ContextVar, so every handler
thread sees only its own request's span, and propagated between
processes with the W3C ``traceparent`` header
(``00-<trace_id>-<span_id>-<flags>``); the sampled flag travels in
``flags`` so one sampling decision at the edge governs the whole path.

Worker threads that execute on BEHALF of a request (the dynamic
batcher, the LM engine driver) don't run under the request's context —
they either adopt it (:func:`use_context`) or record spans
retroactively with explicit start/duration (:func:`record_span`), which
is how queue-wait vs compute splits are attributed to the request that
waited.

Knobs (env, read at import; :func:`configure` overrides in-process):
``HOPS_TPU_TRACING=0`` disables, ``HOPS_TPU_TRACE_SAMPLE`` sets the
root sampling probability (default 1.0), ``HOPS_TPU_TRACE_RING`` the
ring capacity. See docs/operations.md "Tracing & debugging".
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import dataclasses
import os
import random
import re
import threading
import time
from typing import Any, Iterator

from hops_tpu.telemetry.metrics import REGISTRY

TRACEPARENT_HEADER = "traceparent"
#: Request header that asks the serving path to return the per-hop
#: timing breakdown inline in the response (value: ``timeline``).
DEBUG_HEADER = "X-Hops-Debug"

_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)

_m_spans = REGISTRY.counter(
    "hops_tpu_trace_spans_total",
    "Finished spans recorded into the trace ring, per span name",
    labels=("name",),
)


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """The propagatable identity of a span: what a child parents to and
    what ``traceparent`` carries across process boundaries."""

    trace_id: str  # 32 lowercase hex chars
    span_id: str  # 16 lowercase hex chars
    sampled: bool = True

    def traceparent(self) -> str:
        return (
            f"00-{self.trace_id}-{self.span_id}-"
            f"{'01' if self.sampled else '00'}"
        )


def parse_traceparent(header: str | None) -> TraceContext | None:
    """Parse a W3C ``traceparent`` header; None on absent/malformed
    (a bad header must start a fresh trace, never fail the request)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    trace_id, span_id, flags = m.groups()
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None  # the spec's forbidden all-zero ids
    return TraceContext(trace_id, span_id, sampled=bool(int(flags, 16) & 1))


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


class Span:
    """One timed hop of a trace. Context manager: entering activates it
    on the current :mod:`contextvars` context (children find it),
    exiting records it into the tracer ring when sampled. ``_recorded``
    False makes a *carrier* span — pure context, never stored (how
    :func:`use_context` adopts a remote parent without re-recording
    it)."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "sampled", "start",
        "attrs", "events", "duration_s", "_t0", "_tracer", "_recorded",
        "_token",
    )

    def __init__(
        self,
        tracer: "Tracer | None",
        name: str,
        trace_id: str,
        parent_id: str | None,
        sampled: bool,
        attrs: dict[str, Any] | None = None,
        span_id: str | None = None,
        recorded: bool = True,
    ):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id or new_span_id()
        self.parent_id = parent_id
        self.sampled = sampled
        self.start = time.time()
        self._t0 = time.monotonic()
        self.duration_s: float | None = None
        self.attrs: dict[str, Any] = dict(attrs) if attrs else {}
        self.events: list[dict[str, Any]] = []
        self._tracer = tracer
        self._recorded = recorded
        self._token: contextvars.Token | None = None

    # -- annotation (cheap, list/dict ops only) -------------------------------

    def annotate(self, **attrs: Any) -> None:
        self.attrs.update(attrs)

    def add_event(self, name: str, **attrs: Any) -> None:
        self.events.append({"time": time.time(), "name": name, **attrs})

    @property
    def context(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id, self.sampled)

    # -- lifecycle ------------------------------------------------------------

    def __enter__(self) -> "Span":
        self._token = _current.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        if exc_type is not None:
            self.attrs.setdefault("error", f"{exc_type.__name__}: {exc}")
        self.finish()

    def finish(self) -> None:
        if self.duration_s is None:
            self.duration_s = time.monotonic() - self._t0
        if (self._recorded and self.sampled and self._tracer is not None):
            self._tracer._store(self)
            self._tracer = None  # idempotent: a second finish won't re-store

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start,
            "duration_ms": (
                round(self.duration_s * 1e3, 3)
                if self.duration_s is not None else None
            ),
            "attrs": dict(self.attrs),
            "events": list(self.events),
        }


class _NoopSpan:
    """The disabled/unsampled stand-in: every method a no-op, safe to
    enter/annotate from any call site without branching."""

    __slots__ = ()
    name = ""
    trace_id = ""
    span_id = ""
    parent_id = None
    sampled = False
    context = None

    def annotate(self, **attrs: Any) -> None:
        pass

    def add_event(self, name: str, **attrs: Any) -> None:
        pass

    def finish(self) -> None:
        pass

    def to_dict(self) -> dict[str, Any]:
        return {}

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: Any) -> None:
        pass


NOOP_SPAN = _NoopSpan()

#: The active span of the current (thread/task) context. Handler
#: threads each see their own request; worker threads see None unless
#: they adopted a context via :func:`use_context`.
_current: contextvars.ContextVar[Span | None] = contextvars.ContextVar(
    "hops_tpu_trace_span", default=None
)


class Tracer:
    """Sampling recorder with a bounded in-memory ring of finished
    spans. One process-global :data:`TRACER` serves the stack; tests
    may build private ones."""

    def __init__(self, ring_size: int = 512, sample_rate: float = 1.0,
                 seed: int | None = None):
        if not 0.0 <= sample_rate <= 1.0:
            raise ValueError(f"sample_rate must be in [0,1], got {sample_rate}")
        self.sample_rate = sample_rate
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        # guarded by: self._lock
        self._ring: collections.deque[Span] = collections.deque(
            maxlen=ring_size)

    @property
    def ring_size(self) -> int:
        return self._ring.maxlen or 0

    def _sample(self) -> bool:
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        with self._lock:
            return self._rng.random() < self.sample_rate

    def _store(self, span: Span) -> None:
        with self._lock:
            self._ring.append(span)
        _m_spans.inc(name=span.name)

    # -- read surface (GET /debug/traces) -------------------------------------

    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._ring)

    def get_trace(self, trace_id: str) -> list[dict[str, Any]]:
        """All recorded spans of one trace, oldest-start first."""
        rows = [s.to_dict() for s in self.spans() if s.trace_id == trace_id]
        rows.sort(key=lambda r: r["start"])
        return rows

    def traces(self, limit: int = 50,
               since: float | None = None) -> list[dict[str, Any]]:
        """Newest-first trace summaries over the ring. ``since`` (wall
        time) drops traces whose earliest span started before it — the
        ``GET /debug/traces?since=`` incremental-poll contract."""
        by_trace: dict[str, list[Span]] = {}
        for s in self.spans():
            by_trace.setdefault(s.trace_id, []).append(s)
        out = []
        for tid, spans in by_trace.items():
            start = min(s.start for s in spans)
            if since is not None and start < since:
                continue
            end = max(s.start + (s.duration_s or 0.0) for s in spans)
            roots = [s for s in spans if s.parent_id is None]
            # The root can be missing (fell off the ring, or lives in
            # another process) — name the oldest span instead.
            head = roots[0] if roots else min(spans, key=lambda s: s.start)
            out.append({
                "trace_id": tid,
                "root": head.name,
                "spans": len(spans),
                "start": start,
                "duration_ms": round((end - start) * 1e3, 3),
            })
        out.sort(key=lambda r: -r["start"])
        return out[:limit]

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


#: Module-level fast path: every entry point checks this one bool first.
_ENABLED = os.environ.get("HOPS_TPU_TRACING", "1") not in ("0", "false", "")

#: The process-global tracer (ring + sampling decision).
TRACER = Tracer(
    ring_size=int(_env_float("HOPS_TPU_TRACE_RING", 512)),
    sample_rate=_env_float("HOPS_TPU_TRACE_SAMPLE", 1.0),
)


def configure(
    enabled: bool | None = None,
    sample_rate: float | None = None,
    ring_size: int | None = None,
    seed: int | None = None,
) -> Tracer:
    """Reconfigure tracing in-process (tests, benches). Changing
    ``ring_size`` rebuilds the ring (spans are dropped). Returns the
    active tracer."""
    global _ENABLED, TRACER
    if enabled is not None:
        _ENABLED = bool(enabled)
    if ring_size is not None or seed is not None:
        TRACER = Tracer(
            ring_size=ring_size if ring_size is not None else TRACER.ring_size,
            sample_rate=(
                sample_rate if sample_rate is not None else TRACER.sample_rate
            ),
            seed=seed,
        )
    elif sample_rate is not None:
        if not 0.0 <= sample_rate <= 1.0:
            raise ValueError(f"sample_rate must be in [0,1], got {sample_rate}")
        TRACER.sample_rate = sample_rate
    return TRACER


def enabled() -> bool:
    return _ENABLED


# -- the instrumentation surface ----------------------------------------------


def current_span() -> Span | None:
    """The active span, None when the calling context carries none."""
    if not _ENABLED:
        return None
    return _current.get()


def current_context() -> TraceContext | None:
    """The active span's propagatable context (capture this in a
    handler thread to attribute worker-thread time back to the
    request)."""
    span = current_span()
    return span.context if span is not None else None


def current_trace_id() -> str | None:
    span = current_span()
    return span.trace_id if span is not None else None


def start_trace(
    name: str,
    headers: Any = None,
    parent: TraceContext | None = None,
    force_sample: bool = False,
    **attrs: Any,
) -> Span | _NoopSpan:
    """Start a server-side root span: extend the trace an incoming
    ``traceparent`` header (or explicit ``parent``) carries, or start a
    fresh trace under this tracer's sampling decision. The returned
    span is a context manager; entering activates it for the handler's
    context. ``force_sample`` overrides both the local decision and an
    incoming unsampled flag — how ``X-Hops-Debug: timeline`` guarantees
    the breakdown it promises even under aggressive sampling."""
    if not _ENABLED:
        return NOOP_SPAN
    if parent is None and headers is not None:
        get = getattr(headers, "get", None)
        parent = parse_traceparent(get(TRACEPARENT_HEADER) if get else None)
    if parent is not None:
        trace_id, parent_id, sampled = (
            parent.trace_id, parent.span_id, parent.sampled)
    else:
        trace_id, parent_id, sampled = new_trace_id(), None, TRACER._sample()
    if force_sample:
        sampled = True
    if not sampled:
        # Unsampled requests still need context continuity (the
        # decision must ride to downstream hops), but nothing records:
        # carry a context-only span.
        return Span(None, name, trace_id, parent_id, sampled=False,
                    attrs=None, recorded=False)
    return Span(TRACER, name, trace_id, parent_id, sampled=True, attrs=attrs)


def child_span(name: str, **attrs: Any) -> Span | _NoopSpan:
    """A child of the active span — or a no-op when the calling context
    carries none (a child never STARTS a trace; that is the server
    edge's job). This is the one hot-path entry: one bool + one
    contextvar read when tracing is on but the request untraced."""
    if not _ENABLED:
        return NOOP_SPAN
    parent = _current.get()
    if parent is None:
        return NOOP_SPAN
    if not parent.sampled:
        return Span(None, name, parent.trace_id, parent.span_id,
                    sampled=False, recorded=False)
    return Span(TRACER, name, parent.trace_id, parent.span_id,
                sampled=True, attrs=attrs)


def record_span(
    name: str,
    parent: TraceContext | Span | None,
    start: float,
    duration_s: float,
    span_id: str | None = None,
    **attrs: Any,
) -> str | None:
    """Retroactively record a finished span under ``parent`` with an
    explicit wall-clock ``start`` and ``duration_s`` — how worker
    threads (batcher, LM engine) attribute queue-wait and shared
    compute back to the request that experienced them. Returns the new
    span id (None when unrecorded: disabled, no parent, or parent
    unsampled)."""
    if not _ENABLED or parent is None:
        return None
    ctx = parent.context if isinstance(parent, Span) else parent
    if ctx is None or not ctx.sampled:
        return None
    span = Span(TRACER, name, ctx.trace_id, ctx.span_id, sampled=True,
                attrs=attrs, span_id=span_id)
    span.start = start
    span.duration_s = max(0.0, float(duration_s))
    span.finish()
    return span.span_id


@contextlib.contextmanager
def use_context(ctx: TraceContext | None) -> Iterator[None]:
    """Adopt a request's context in a worker thread for the with-block:
    child spans created inside parent to ``ctx`` (the carrier span
    itself is never recorded). ``None`` adopts nothing."""
    if not _ENABLED or ctx is None:
        yield
        return
    carrier = Span(None, "carrier", ctx.trace_id, None, sampled=ctx.sampled,
                   span_id=ctx.span_id, recorded=False)
    token = _current.set(carrier)
    try:
        yield
    finally:
        _current.reset(token)


def annotate(**attrs: Any) -> None:
    """Attach attributes to the active span; no-op without one (how
    resilience/faultinject annotate whatever request they fire under)."""
    if not _ENABLED:
        return
    span = _current.get()
    if span is not None:
        span.annotate(**attrs)


def add_event(name: str, **attrs: Any) -> None:
    """Append a timestamped event to the active span; no-op without
    one."""
    if not _ENABLED:
        return
    span = _current.get()
    if span is not None:
        span.add_event(name, **attrs)


def timeline(span: Span | _NoopSpan | None) -> list[dict[str, Any]]:
    """The per-hop timing breakdown for ``span``'s trace, as served
    inline when a request carries ``X-Hops-Debug: timeline``: every
    recorded span of the trace in this process's ring, plus ``span``
    itself (duration-so-far) when it hasn't finished yet, sorted by
    start time."""
    if span is None or isinstance(span, _NoopSpan) or not span.sampled:
        return []
    rows = TRACER.get_trace(span.trace_id)
    if not any(r["span_id"] == span.span_id for r in rows):
        d = span.to_dict()
        d["duration_ms"] = round((time.monotonic() - span._t0) * 1e3, 3)
        d["in_progress"] = True
        rows.append(d)
        rows.sort(key=lambda r: r["start"])
    return rows


def inject_headers(headers: dict[str, str]) -> dict[str, str]:
    """Add the active span's ``traceparent`` to an outgoing header dict
    (mutates and returns it); no-op without an active span."""
    ctx = current_context()
    if ctx is not None:
        headers[TRACEPARENT_HEADER] = ctx.traceparent()
    return headers
