"""Span timers: wall-clock blocks feeding latency histograms.

``with span("hops_tpu_serving_request", model=name): ...`` times the
block into a ``<name>_seconds`` histogram in the global registry;
``@timed()`` does the same for whole functions. When the JAX profiler
is active (``runtime/diagnostics.trace``), each span additionally opens
a ``jax.profiler.TraceAnnotation`` so spans nest inside the XProf
timeline — one annotation vocabulary across metrics and traces.

:class:`StepTimer` is the step-loop shape of the same idea: one
``tick()`` per training step feeds the step-time histogram, the
steps/examples counters (PromQL ``rate()`` gives steps/sec and
examples/sec), and the ``hops_tpu_heartbeat_time`` gauge that
``runtime/preemption.py`` maintains and ``diagnostics.Watchdog`` can
watch.
"""

from __future__ import annotations

import contextlib
import functools
import re
import sys
import time
from typing import Any, Callable, Iterator

from hops_tpu.telemetry.metrics import DEFAULT_BUCKETS, REGISTRY, Registry
from hops_tpu.telemetry import tracing

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")

#: The well-known heartbeat gauge names (see module docstring). The
#: wall-clock gauge is for scrapes ("when did this loop last beat");
#: the monotonic twin is what in-process watchdogs compare against —
#: immune to NTP steps, meaningless across processes.
HEARTBEAT_GAUGE = "hops_tpu_heartbeat_time"
HEARTBEAT_MONO_GAUGE = "hops_tpu_heartbeat_monotonic"


def _sanitize(name: str) -> str:
    return _NAME_RE.sub("_", name)


def _histogram(name: str, labels: tuple[str, ...], registry: Registry):
    return registry.histogram(
        f"{_sanitize(name)}_seconds",
        f"Duration of {name} spans",
        labels=labels,
        buckets=DEFAULT_BUCKETS,
    )


@contextlib.contextmanager
def span(name: str, registry: Registry = REGISTRY,
         **labels: Any) -> Iterator[None]:
    """Time the block into ``<name>_seconds{**labels}``. Label NAMES
    must be consistent across uses of one span name (they declare the
    histogram's label set). Exceptions propagate but the duration is
    still recorded — error latency is latency.

    When the calling context carries an active distributed trace
    (``telemetry/tracing.py``), the block additionally records a child
    tracing span of the same name — one annotation vocabulary across
    metrics, XProf timelines, and request traces — and the histogram
    observation carries the trace id as an exemplar, so a latency
    bucket links back to a concrete trace."""
    hist = _histogram(name, tuple(sorted(labels)), registry)
    # Nest inside an active profiler trace without importing jax (and
    # dragging a backend up) from processes that never touched it.
    jax = sys.modules.get("jax")
    annotation = (
        jax.profiler.TraceAnnotation(name) if jax is not None
        else contextlib.nullcontext()
    )
    # Joins the active request trace; a no-op outside one (and the
    # whole lookup is one bool when tracing is disabled).
    tspan = tracing.child_span(name, **labels)
    start = time.monotonic()
    try:
        with annotation, tspan:
            yield
    finally:
        hist.observe(time.monotonic() - start,
                     exemplar=tracing.current_trace_id(), **labels)


def timed(name: str | None = None, registry: Registry = REGISTRY,
          **labels: Any) -> Callable:
    """Decorator form of :func:`span`; the metric name defaults to the
    function's qualified name (``hops_tpu_span_<module>_<fn>``)."""

    def deco(fn: Callable) -> Callable:
        span_name = name or _sanitize(
            f"hops_tpu_span_{fn.__module__}_{fn.__qualname__}"
        )

        @functools.wraps(fn)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            with span(span_name, registry=registry, **labels):
                return fn(*args, **kwargs)

        return wrapper

    return deco


class StepTimer:
    """Step-cadence telemetry for training/experiment loops.

    Call :meth:`tick` once per completed step (``examples=`` the batch
    size if known). Feeds, all labelled ``loop=<name>``:

    - ``hops_tpu_step_seconds`` — step-time histogram (time between
      consecutive ticks; the first tick only arms the clock),
    - ``hops_tpu_steps_total`` / ``hops_tpu_examples_total`` —
      counters whose scrape-side ``rate()`` is steps/sec and
      examples/sec,
    - ``hops_tpu_heartbeat_time`` — unix time of the last tick, the
      gauge ``diagnostics.Watchdog(watch_heartbeat_gauge=True)`` reads
      instead of requiring explicit ``heartbeat()`` calls.
    """

    def __init__(self, loop: str = "train", registry: Registry = REGISTRY):
        self.loop = loop
        self._step_seconds = registry.histogram(
            "hops_tpu_step_seconds", "Training step wall time",
            labels=("loop",),
        ).labels(loop=loop)
        self._steps = registry.counter(
            "hops_tpu_steps_total", "Training steps completed",
            labels=("loop",),
        ).labels(loop=loop)
        self._examples = registry.counter(
            "hops_tpu_examples_total", "Training examples consumed",
            labels=("loop",),
        ).labels(loop=loop)
        self._heartbeat = registry.gauge(
            HEARTBEAT_GAUGE,
            "Unix time of the last step-boundary heartbeat, per loop",
            labels=("loop",),
        ).labels(loop=loop)
        self._heartbeat_mono = registry.gauge(
            HEARTBEAT_MONO_GAUGE,
            "Monotonic clock of the last step-boundary heartbeat, per "
            "loop (for in-process watchdogs; not comparable across "
            "processes)",
            labels=("loop",),
        ).labels(loop=loop)
        self._last: float | None = None

    def _beat(self) -> None:
        self._heartbeat.set(time.time())
        self._heartbeat_mono.set(time.monotonic())

    def arm(self) -> None:
        """Reset the step clock without recording anything — call at a
        loop (re)start so the first tick doesn't measure idle time
        spanning two runs."""
        self._last = time.monotonic()
        self._beat()

    def tick(self, examples: int | None = None) -> None:
        now = time.monotonic()
        if self._last is not None:
            self._step_seconds.observe(now - self._last)
        self._last = now
        self._steps.inc()
        if examples:
            self._examples.inc(examples)
        self._beat()
