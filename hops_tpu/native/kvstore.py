"""ctypes binding for the native KV engine (kvstore.cc).

Exposes the same backend protocol as ``online._SqliteKV`` so
``OnlineStore`` can swap engines transparently.
"""

from __future__ import annotations

import ctypes
import struct
import threading
from typing import Iterator

from hops_tpu import native


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes.c_char_p
    u32, u64 = ctypes.c_uint32, ctypes.c_uint64
    lib.kv_open.restype = ctypes.c_void_p
    lib.kv_open.argtypes = [c]
    lib.kv_put.restype = ctypes.c_int
    lib.kv_put.argtypes = [ctypes.c_void_p, c, u32, c, u32]
    lib.kv_get.restype = ctypes.c_int
    lib.kv_get.argtypes = [
        ctypes.c_void_p, c, u32,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_char)), ctypes.POINTER(u32),
    ]
    lib.kv_delete.restype = ctypes.c_int
    lib.kv_delete.argtypes = [ctypes.c_void_p, c, u32]
    lib.kv_get_many.restype = ctypes.c_int
    lib.kv_get_many.argtypes = [
        ctypes.c_void_p, c, u32,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_char)), ctypes.POINTER(u64),
    ]
    lib.kv_count.restype = u64
    lib.kv_count.argtypes = [ctypes.c_void_p]
    lib.kv_flush.argtypes = [ctypes.c_void_p]
    lib.kv_compact.restype = ctypes.c_int64
    lib.kv_compact.argtypes = [ctypes.c_void_p]
    lib.kv_scan.restype = ctypes.c_void_p
    lib.kv_scan.argtypes = [ctypes.c_void_p]
    lib.kv_scan_next.restype = ctypes.c_int
    lib.kv_scan_next.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_char)), ctypes.POINTER(u32),
    ]
    lib.kv_scan_close.argtypes = [ctypes.c_void_p]
    lib.kv_free.argtypes = [ctypes.POINTER(ctypes.c_char)]
    lib.kv_close.argtypes = [ctypes.c_void_p]
    return lib


_bind_lock = threading.Lock()
_bound: ctypes.CDLL | None = None  # guarded by: _bind_lock


def _lib() -> ctypes.CDLL:
    # Two threads opening stores concurrently (sharded online store
    # startup) must not race the check-then-bind: an unguarded double
    # _bind would hand one of them a CDLL whose restype/argtypes are
    # being mutated mid-flight.
    global _bound
    with _bind_lock:
        if _bound is None:
            raw = native.load()
            if raw is None:
                raise RuntimeError(
                    "native library not built; run `make -C hops_tpu/native`"
                )
            _bound = _bind(raw)
        return _bound


def available() -> bool:
    return native.available()


class NativeKV:
    #: The mmap'd log + open-addressing index are NOT reader-safe while
    #: a put grows the log or a compact rewrites it: readers must hold
    #: the owning store's writer lock (see ``OnlineStore._read``).
    reader_safe = False

    def __init__(self, path: str):
        self._lib = _lib()
        self._h = self._lib.kv_open(path.encode())
        if not self._h:
            raise OSError(f"kv_open failed for {path}")

    def put(self, key: str, value: str) -> None:
        k, v = key.encode(), value.encode()
        rc = self._lib.kv_put(self._h, k, len(k), v, len(v))
        if rc != 0:
            raise OSError(f"kv_put failed (rc={rc})")

    def get(self, key: str) -> str | None:
        k = key.encode()
        out = ctypes.POINTER(ctypes.c_char)()
        out_len = ctypes.c_uint32()
        rc = self._lib.kv_get(self._h, k, len(k), ctypes.byref(out), ctypes.byref(out_len))
        if rc != 0:
            return None
        try:
            return ctypes.string_at(out, out_len.value).decode()
        finally:
            self._lib.kv_free(out)

    def delete(self, key: str) -> None:
        k = key.encode()
        self._lib.kv_delete(self._h, k, len(k))

    def get_many(self, keys: list[str]) -> list[str | None]:
        """Batched point lookup in input order (None = miss): the keys
        pack into one buffer, cross the FFI once, and the C side
        resolves the whole batch under ONE lock acquisition — the
        online store's multi-get path stops paying per-key ctypes +
        mutex overhead."""
        if not keys:
            return []
        parts = []
        for key in keys:
            k = key.encode()
            parts.append(struct.pack("<I", len(k)))
            parts.append(k)
        packed = b"".join(parts)
        out = ctypes.POINTER(ctypes.c_char)()
        out_len = ctypes.c_uint64()
        rc = self._lib.kv_get_many(
            self._h, packed, len(keys), ctypes.byref(out), ctypes.byref(out_len)
        )
        if rc != 0:
            raise OSError(f"kv_get_many failed (rc={rc})")
        try:
            blob = ctypes.string_at(out, out_len.value)
        finally:
            self._lib.kv_free(out)
        vals: list[str | None] = []
        pos = 0
        for _ in keys:
            (vlen,) = struct.unpack_from("<I", blob, pos)
            pos += 4
            if vlen == 0xFFFFFFFF:
                vals.append(None)
                continue
            vals.append(blob[pos:pos + vlen].decode())
            pos += vlen
        return vals

    def scan(self) -> Iterator[str]:
        it = self._lib.kv_scan(self._h)
        try:
            out = ctypes.POINTER(ctypes.c_char)()
            out_len = ctypes.c_uint32()
            while self._lib.kv_scan_next(it, ctypes.byref(out), ctypes.byref(out_len)) == 0:
                try:
                    yield ctypes.string_at(out, out_len.value).decode()
                finally:
                    self._lib.kv_free(out)
        finally:
            self._lib.kv_scan_close(it)

    def count(self) -> int:
        return int(self._lib.kv_count(self._h))

    def compact(self) -> int:
        return int(self._lib.kv_compact(self._h))

    def flush(self) -> None:
        self._lib.kv_flush(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.kv_close(self._h)
            self._h = None
