// Record-IO: length-prefixed record files with an offset index for O(1)
// random access — the native data-loader piece of the TPU build.
//
// The reference materialized training datasets as TFRecord files read by
// tf.data inside the Spark executors (training_datasets.ipynb:409-429,
// SURVEY.md §2.6); the heavy IO lived in TF's native ops. Here training
// datasets can materialize to this format and the feeder does shuffled
// per-record reads through this engine (ctypes), keeping the Python side
// to batch assembly only.
//
// Layout: <path>      = [u32 len][bytes]...
//         <path>.idx  = [u64 offset]... (offset of each record's header)

#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Writer {
  std::FILE* f;
  std::string path;
  std::vector<uint64_t> offsets;
  uint64_t pos = 0;
};

struct Reader {
  std::FILE* f;
  int fd;  // for positioned (pread) batch reads
  std::vector<uint64_t> offsets;
  std::mutex mu;
};

}  // namespace

extern "C" {

void* rio_writer_open(const char* path) {
  auto* w = new Writer();
  w->path = path;
  w->f = std::fopen(path, "wb");
  if (!w->f) {
    delete w;
    return nullptr;
  }
  return w;
}

int rio_write(void* h, const char* data, uint32_t len) {
  auto* w = static_cast<Writer*>(h);
  uint32_t hdr = len;
  if (std::fwrite(&hdr, 1, sizeof hdr, w->f) != sizeof hdr) return -1;
  if (len && std::fwrite(data, 1, len, w->f) != len) return -1;
  w->offsets.push_back(w->pos);
  w->pos += sizeof hdr + len;
  return 0;
}

uint64_t rio_writer_close(void* h) {
  auto* w = static_cast<Writer*>(h);
  std::fflush(w->f);
  std::fclose(w->f);
  uint64_t n = w->offsets.size();
  std::FILE* idx = std::fopen((w->path + ".idx").c_str(), "wb");
  if (idx) {
    std::fwrite(w->offsets.data(), sizeof(uint64_t), w->offsets.size(), idx);
    std::fclose(idx);
  }
  delete w;
  return n;
}

void* rio_reader_open(const char* path) {
  auto* r = new Reader();
  r->f = std::fopen(path, "rb");
  if (!r->f) {
    delete r;
    return nullptr;
  }
  std::string idx_path = std::string(path) + ".idx";
  std::FILE* idx = std::fopen(idx_path.c_str(), "rb");
  if (idx) {
    std::fseek(idx, 0, SEEK_END);
    long bytes = std::ftell(idx);
    std::fseek(idx, 0, SEEK_SET);
    r->offsets.resize((size_t)bytes / sizeof(uint64_t));
    if (std::fread(r->offsets.data(), 1, (size_t)bytes, idx) != (size_t)bytes)
      r->offsets.clear();
    std::fclose(idx);
  }
  if (r->offsets.empty()) {
    // No/torn index: rebuild by scanning the log.
    uint64_t pos = 0;
    for (;;) {
      uint32_t len;
      std::fseek(r->f, (long)pos, SEEK_SET);
      if (std::fread(&len, 1, sizeof len, r->f) != sizeof len) break;
      r->offsets.push_back(pos);
      pos += sizeof len + len;
    }
  }
  r->fd = fileno(r->f);
  return r;
}

uint64_t rio_num_records(void* h) {
  return static_cast<Reader*>(h)->offsets.size();
}

// *out malloc'd; free via rio_free.
int rio_read(void* h, uint64_t i, char** out, uint32_t* out_len) {
  auto* r = static_cast<Reader*>(h);
  std::lock_guard<std::mutex> lock(r->mu);
  if (i >= r->offsets.size()) return -1;
  std::fseek(r->f, (long)r->offsets[i], SEEK_SET);
  uint32_t len;
  if (std::fread(&len, 1, sizeof len, r->f) != sizeof len) return -2;
  char* buf = (char*)std::malloc(len ? len : 1);
  if (len && std::fread(buf, 1, len, r->f) != len) {
    std::free(buf);
    return -2;
  }
  *out = buf;
  *out_len = len;
  return 0;
}

void rio_free(char* p) { std::free(p); }

// Gather the records named by ``indices`` into ONE malloc'd buffer,
// packed back-to-back in the given order. Record lengths land in
// ``lens`` (caller-allocated, n entries); *out_total is the packed
// size. Positioned reads (pread) on the shared fd — thread-safe per
// POSIX, no seek contention, no mutex — fanned over ``n_threads``
// worker threads. This is the feeder's batch path: one ctypes call
// per training batch instead of one per record.
int rio_read_batch(void* h, const uint64_t* indices, uint32_t n,
                   uint32_t n_threads, char** out, uint64_t* out_total,
                   uint64_t* lens) {
  auto* r = static_cast<Reader*>(h);
  const uint64_t nrec = r->offsets.size();
  uint64_t total = 0;
  for (uint32_t i = 0; i < n; ++i) {
    const uint64_t idx = indices[i];
    if (idx >= nrec) return -1;
    if (idx + 1 < nrec) {
      // Records are contiguous, so consecutive offsets give the length
      // without touching the disk.
      if (r->offsets[idx + 1] < r->offsets[idx] + sizeof(uint32_t)) return -2;
      lens[i] = r->offsets[idx + 1] - r->offsets[idx] - sizeof(uint32_t);
    } else {
      // Only the final record needs its header consulted: a stale .idx
      // must not stretch it over trailing unindexed data.
      uint32_t hdr;
      if (pread(r->fd, &hdr, sizeof hdr, (off_t)r->offsets[idx]) !=
          (ssize_t)sizeof hdr)
        return -2;
      lens[i] = hdr;
    }
    total += lens[i];
  }
  char* buf = (char*)std::malloc(total ? total : 1);
  if (!buf) return -3;

  // Prefix positions of each record inside the packed buffer.
  std::vector<uint64_t> dst(n);
  uint64_t pos = 0;
  for (uint32_t i = 0; i < n; ++i) {
    dst[i] = pos;
    pos += lens[i];
  }

  const uint32_t workers =
      n_threads == 0 ? 1 : (n_threads < n ? n_threads : (n ? n : 1));
  std::vector<int> rcs(workers, 0);
  auto work = [&](uint32_t w) {
    for (uint32_t i = w; i < n; i += workers) {
      uint64_t remaining = lens[i];
      uint64_t src = r->offsets[indices[i]] + sizeof(uint32_t);
      char* d = buf + dst[i];
      while (remaining) {
        ssize_t got = pread(r->fd, d, remaining, (off_t)src);
        if (got <= 0) {
          rcs[w] = -4;
          return;
        }
        remaining -= (uint64_t)got;
        src += (uint64_t)got;
        d += got;
      }
    }
  };
  if (workers == 1) {
    work(0);
  } else {
    std::vector<std::thread> threads;
    threads.reserve(workers);
    for (uint32_t w = 0; w < workers; ++w) threads.emplace_back(work, w);
    for (auto& t : threads) t.join();
  }
  for (uint32_t w = 0; w < workers; ++w) {
    if (rcs[w] != 0) {
      std::free(buf);
      return rcs[w];
    }
  }
  *out = buf;
  *out_total = total;
  return 0;
}

void rio_reader_close(void* h) {
  auto* r = static_cast<Reader*>(h);
  std::fclose(r->f);
  delete r;
}

}  // extern "C"
