"""ctypes binding for the native record-IO engine (recordio.cc), with a
pure-Python fallback implementing the same on-disk format."""

from __future__ import annotations

import ctypes
import struct
from pathlib import Path
from typing import Iterator

from hops_tpu import native

_HDR = struct.Struct("<I")
_IDX = struct.Struct("<Q")


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u32, u64 = ctypes.c_uint32, ctypes.c_uint64
    lib.rio_writer_open.restype = ctypes.c_void_p
    lib.rio_writer_open.argtypes = [ctypes.c_char_p]
    lib.rio_write.restype = ctypes.c_int
    lib.rio_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p, u32]
    lib.rio_writer_close.restype = u64
    lib.rio_writer_close.argtypes = [ctypes.c_void_p]
    lib.rio_reader_open.restype = ctypes.c_void_p
    lib.rio_reader_open.argtypes = [ctypes.c_char_p]
    lib.rio_num_records.restype = u64
    lib.rio_num_records.argtypes = [ctypes.c_void_p]
    lib.rio_read.restype = ctypes.c_int
    lib.rio_read.argtypes = [
        ctypes.c_void_p, u64,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_char)), ctypes.POINTER(u32),
    ]
    lib.rio_read_batch.restype = ctypes.c_int
    lib.rio_read_batch.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(u64), u32, u32,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_char)), ctypes.POINTER(u64),
        ctypes.POINTER(u64),
    ]
    lib.rio_free.argtypes = [ctypes.POINTER(ctypes.c_char)]
    lib.rio_reader_close.argtypes = [ctypes.c_void_p]
    return lib


_bound: ctypes.CDLL | None = None
_bind_failed = False


def _lib() -> ctypes.CDLL | None:
    global _bound, _bind_failed
    if _bound is None and not _bind_failed and native.available():
        try:
            _bound = _bind(native.load())
        except AttributeError:
            # A stale libhops_native.so missing newer symbols must not
            # take down the whole binding — degrade to pure Python (the
            # documented contract) until the library is rebuilt.
            _bind_failed = True
    return _bound


class RecordWriter:
    """Append records; index written on close."""

    def __init__(self, path: str | Path):
        self._path = str(path)
        lib = _lib()
        if lib is not None:
            self._h, self._lib = lib.rio_writer_open(self._path.encode()), lib
            if not self._h:
                raise OSError(f"rio_writer_open failed for {path}")
        else:
            self._lib = None
            self._f = open(self._path, "wb")
            self._offsets: list[int] = []
            self._pos = 0

    def write(self, record: bytes) -> None:
        if self._lib is not None:
            if self._lib.rio_write(self._h, record, len(record)) != 0:
                raise OSError("rio_write failed")
        else:
            self._f.write(_HDR.pack(len(record)))
            self._f.write(record)
            self._offsets.append(self._pos)
            self._pos += _HDR.size + len(record)

    def close(self) -> int:
        if self._lib is not None:
            return int(self._lib.rio_writer_close(self._h))
        self._f.close()
        with open(self._path + ".idx", "wb") as idx:
            for off in self._offsets:
                idx.write(_IDX.pack(off))
        return len(self._offsets)

    def __enter__(self) -> "RecordWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class RecordReader:
    """O(1) random access over a record file.

    Thread-safety: the native path is safe to share across threads
    (``rio_read``/``rio_read_batch`` use positioned ``pread`` — no
    seek state); the pure-Python fallback serializes its shared file
    object's seek+read under a lock, so a reader handed to a decode
    pool (``featurestore/loader.py``) behaves identically on both
    paths — the fallback just doesn't overlap its reads."""

    def __init__(self, path: str | Path):
        self._path = str(path)
        lib = _lib()
        if lib is not None:
            self._h, self._lib = lib.rio_reader_open(self._path.encode()), lib
            if not self._h:
                raise OSError(f"rio_reader_open failed for {path}")
            self._n = int(lib.rio_num_records(self._h))
        else:
            self._lib = None
            import threading

            self._f_lock = threading.Lock()
            self._f = open(self._path, "rb")  # guarded by: self._f_lock
            idx = Path(self._path + ".idx")
            if idx.exists():
                raw = idx.read_bytes()
                self._offsets = [
                    _IDX.unpack_from(raw, i * _IDX.size)[0]
                    for i in range(len(raw) // _IDX.size)
                ]
            else:
                self._offsets = []
                pos = 0
                while True:
                    self._f.seek(pos)
                    hdr = self._f.read(_HDR.size)
                    if len(hdr) < _HDR.size:
                        break
                    self._offsets.append(pos)
                    pos += _HDR.size + _HDR.unpack(hdr)[0]
            self._n = len(self._offsets)

    def __len__(self) -> int:
        return self._n

    def read(self, i: int) -> bytes:
        if self._lib is not None:
            out = ctypes.POINTER(ctypes.c_char)()
            out_len = ctypes.c_uint32()
            rc = self._lib.rio_read(self._h, i, ctypes.byref(out), ctypes.byref(out_len))
            if rc != 0:
                raise IndexError(f"record {i} (rc={rc})")
            try:
                return ctypes.string_at(out, out_len.value)
            finally:
                self._lib.rio_free(out)
        off = self._offsets[i]
        with self._f_lock:
            self._f.seek(off)
            (length,) = _HDR.unpack(self._f.read(_HDR.size))
            return self._f.read(length)

    def read_batch(self, indices, n_threads: int = 4) -> list[bytes]:
        """Gather many records in ONE native call.

        The engine packs the records back-to-back via positioned reads
        (pread — no seek contention, no reader mutex) fanned over
        ``n_threads``; record lengths come from consecutive index
        offsets (no header reads except the final record). Measured
        1.2x over per-record reads single-threaded on a 1-core
        warm-cache box; the thread fan-out adds more on multi-core TPU
        hosts and cold storage.
        """
        idx = list(indices)
        if self._lib is None or not idx:
            return [self.read(i) for i in idx]
        n = len(idx)
        arr = (ctypes.c_uint64 * n)(*idx)
        lens = (ctypes.c_uint64 * n)()
        out = ctypes.POINTER(ctypes.c_char)()
        total = ctypes.c_uint64()
        rc = self._lib.rio_read_batch(
            self._h, arr, n, n_threads, ctypes.byref(out),
            ctypes.byref(total), lens,
        )
        if rc == -1:
            raise IndexError(f"batch read: index out of range (n={n})")
        if rc != 0:
            raise OSError(f"batch read of {n} records failed: "
                          f"{'I/O error' if rc in (-2, -4) else 'allocation failure'} "
                          f"(rc={rc})")
        # Slice each record straight out of the native buffer — one copy
        # per record, no whole-buffer bytes intermediate.
        try:
            base = ctypes.addressof(out.contents)
            records, pos = [], 0
            for i in range(n):
                records.append(ctypes.string_at(base + pos, lens[i]))
                pos += lens[i]
        finally:
            self._lib.rio_free(out)
        return records

    def __iter__(self) -> Iterator[bytes]:
        return (self.read(i) for i in range(self._n))

    def close(self) -> None:
        if self._lib is not None:
            if self._h:
                self._lib.rio_reader_close(self._h)
                self._h = None
        else:
            # Under the read lock: closing mid-read would raise a
            # ValueError on whichever decode worker holds the file.
            with self._f_lock:
                self._f.close()

    def __enter__(self) -> "RecordReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
