"""ctypes binding for the native record-IO engine (recordio.cc), with a
pure-Python fallback implementing the same on-disk format."""

from __future__ import annotations

import ctypes
import struct
from pathlib import Path
from typing import Iterator

from hops_tpu import native

_HDR = struct.Struct("<I")
_IDX = struct.Struct("<Q")


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u32, u64 = ctypes.c_uint32, ctypes.c_uint64
    lib.rio_writer_open.restype = ctypes.c_void_p
    lib.rio_writer_open.argtypes = [ctypes.c_char_p]
    lib.rio_write.restype = ctypes.c_int
    lib.rio_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p, u32]
    lib.rio_writer_close.restype = u64
    lib.rio_writer_close.argtypes = [ctypes.c_void_p]
    lib.rio_reader_open.restype = ctypes.c_void_p
    lib.rio_reader_open.argtypes = [ctypes.c_char_p]
    lib.rio_num_records.restype = u64
    lib.rio_num_records.argtypes = [ctypes.c_void_p]
    lib.rio_read.restype = ctypes.c_int
    lib.rio_read.argtypes = [
        ctypes.c_void_p, u64,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_char)), ctypes.POINTER(u32),
    ]
    lib.rio_free.argtypes = [ctypes.POINTER(ctypes.c_char)]
    lib.rio_reader_close.argtypes = [ctypes.c_void_p]
    return lib


_bound: ctypes.CDLL | None = None


def _lib() -> ctypes.CDLL | None:
    global _bound
    if _bound is None and native.available():
        _bound = _bind(native.load())
    return _bound


class RecordWriter:
    """Append records; index written on close."""

    def __init__(self, path: str | Path):
        self._path = str(path)
        lib = _lib()
        if lib is not None:
            self._h, self._lib = lib.rio_writer_open(self._path.encode()), lib
            if not self._h:
                raise OSError(f"rio_writer_open failed for {path}")
        else:
            self._lib = None
            self._f = open(self._path, "wb")
            self._offsets: list[int] = []
            self._pos = 0

    def write(self, record: bytes) -> None:
        if self._lib is not None:
            if self._lib.rio_write(self._h, record, len(record)) != 0:
                raise OSError("rio_write failed")
        else:
            self._f.write(_HDR.pack(len(record)))
            self._f.write(record)
            self._offsets.append(self._pos)
            self._pos += _HDR.size + len(record)

    def close(self) -> int:
        if self._lib is not None:
            return int(self._lib.rio_writer_close(self._h))
        self._f.close()
        with open(self._path + ".idx", "wb") as idx:
            for off in self._offsets:
                idx.write(_IDX.pack(off))
        return len(self._offsets)

    def __enter__(self) -> "RecordWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class RecordReader:
    """O(1) random access over a record file."""

    def __init__(self, path: str | Path):
        self._path = str(path)
        lib = _lib()
        if lib is not None:
            self._h, self._lib = lib.rio_reader_open(self._path.encode()), lib
            if not self._h:
                raise OSError(f"rio_reader_open failed for {path}")
            self._n = int(lib.rio_num_records(self._h))
        else:
            self._lib = None
            self._f = open(self._path, "rb")
            idx = Path(self._path + ".idx")
            if idx.exists():
                raw = idx.read_bytes()
                self._offsets = [
                    _IDX.unpack_from(raw, i * _IDX.size)[0]
                    for i in range(len(raw) // _IDX.size)
                ]
            else:
                self._offsets = []
                pos = 0
                while True:
                    self._f.seek(pos)
                    hdr = self._f.read(_HDR.size)
                    if len(hdr) < _HDR.size:
                        break
                    self._offsets.append(pos)
                    pos += _HDR.size + _HDR.unpack(hdr)[0]
            self._n = len(self._offsets)

    def __len__(self) -> int:
        return self._n

    def read(self, i: int) -> bytes:
        if self._lib is not None:
            out = ctypes.POINTER(ctypes.c_char)()
            out_len = ctypes.c_uint32()
            rc = self._lib.rio_read(self._h, i, ctypes.byref(out), ctypes.byref(out_len))
            if rc != 0:
                raise IndexError(f"record {i} (rc={rc})")
            try:
                return ctypes.string_at(out, out_len.value)
            finally:
                self._lib.rio_free(out)
        off = self._offsets[i]
        self._f.seek(off)
        (length,) = _HDR.unpack(self._f.read(_HDR.size))
        return self._f.read(length)

    def __iter__(self) -> Iterator[bytes]:
        return (self.read(i) for i in range(self._n))

    def close(self) -> None:
        if self._lib is not None:
            if self._h:
                self._lib.rio_reader_close(self._h)
                self._h = None
        else:
            self._f.close()

    def __enter__(self) -> "RecordReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
