// Embedded key-value store: the native engine behind the online feature
// store (hops_tpu/featurestore/online.py).
//
// The reference's online store was MySQL Cluster (NDB) reached over JDBC
// prepared statements (SURVEY.md §2.6 — "implied native"). This is the
// TPU build's equivalent: a log-structured store with an in-memory hash
// index, giving O(1) point lookups for `get_serving_vector` without a
// database server.
//
// Format: append-only log of records
//   [u32 klen][u32 vlen][key][value]        (vlen == 0xFFFFFFFF: tombstone)
// On open the log is scanned once to rebuild the index; `compact`
// rewrites the log with only live records.
//
// C ABI only (consumed via ctypes — no pybind11 in the image).

#include <sys/mman.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint32_t kTombstone = 0xFFFFFFFFu;

struct Entry {
  uint64_t offset;  // offset of the value bytes in the log
  uint32_t length;
};

struct Store {
  std::FILE* f = nullptr;
  std::string path;
  std::unordered_map<std::string, Entry> index;
  uint64_t end = 0;  // current append offset
  std::mutex mu;
  // Read-only mmap of the log: point lookups are a hash probe plus a
  // memcpy out of the mapping instead of fseek+fread per key (the
  // fseek path measured ~2x SLOWER than sqlite's batched SELECT; the
  // mapping is what makes the native engine the fast online backend).
  // Remapped lazily when the log outgrows it; `flushed` tracks how far
  // the stdio stream has been pushed into the file — a MAP_SHARED
  // mapping sees file bytes, never the stream's private buffer.
  char* map = nullptr;
  uint64_t map_len = 0;
  uint64_t flushed = 0;
};

void drop_mapping(Store* s) {
  if (s->map != nullptr) munmap(s->map, s->map_len);
  s->map = nullptr;
  s->map_len = 0;
}

// Make [0, s->end) readable through s->map. Caller holds s->mu.
// Returns false when the log is empty or mmap fails (callers fall back
// to the fseek+fread path).
bool ensure_mapped(Store* s) {
  if (s->flushed < s->end) {
    std::fflush(s->f);
    s->flushed = s->end;
  }
  if (s->map != nullptr && s->map_len >= s->end) return true;
  drop_mapping(s);
  if (s->end == 0) return false;  // empty log: nothing to map
  // Map the whole file (it may exceed `end` only transiently); the
  // file can only grow, so headroom beyond `end` stays valid.
  std::fseek(s->f, 0, SEEK_END);
  uint64_t file_size = (uint64_t)std::ftell(s->f);
  if (file_size < s->end) return false;
  void* m = mmap(nullptr, file_size, PROT_READ, MAP_SHARED, fileno(s->f), 0);
  if (m == MAP_FAILED) return false;
  s->map = (char*)m;
  s->map_len = file_size;
  return true;
}

bool read_exact(std::FILE* f, void* buf, size_t n) {
  return std::fread(buf, 1, n, f) == n;
}

// Scan the log, rebuilding the index. A torn tail record (crash mid-
// write) is detected by bounds-checking against the real file size —
// fseek past EOF "succeeds", so size is the only reliable signal.
bool rebuild_index(Store* s) {
  std::fseek(s->f, 0, SEEK_END);
  uint64_t file_size = (uint64_t)std::ftell(s->f);
  std::fseek(s->f, 0, SEEK_SET);
  uint64_t pos = 0;
  std::vector<char> kbuf;
  for (;;) {
    uint32_t hdr[2];
    if (pos + sizeof hdr > file_size) break;
    if (!read_exact(s->f, hdr, sizeof hdr)) break;
    uint32_t klen = hdr[0], vlen = hdr[1];
    if (pos + sizeof hdr + klen > file_size) break;
    kbuf.resize(klen);
    if (!read_exact(s->f, kbuf.data(), klen)) break;
    std::string key(kbuf.data(), klen);
    if (vlen == kTombstone) {
      s->index.erase(key);
      pos += sizeof hdr + klen;
    } else {
      uint64_t voff = pos + sizeof hdr + klen;
      if (voff + vlen > file_size) break;  // torn value: drop tail record
      s->index[key] = Entry{voff, vlen};
      pos = voff + vlen;
      std::fseek(s->f, (long)pos, SEEK_SET);
    }
  }
  s->end = pos;
  s->flushed = pos;  // everything scanned is already in the file
  if (pos < file_size) {
    // Torn tail: cut it off. Leaving the garbage in place would let a
    // shorter subsequent append partially overwrite it, and the NEXT
    // reopen could then parse the leftover bytes as phantom records.
    std::fflush(s->f);
    if (ftruncate(fileno(s->f), (off_t)pos) != 0) return false;
  }
  return true;
}

int append_record(Store* s, const char* k, uint32_t klen, const char* v,
                  uint32_t vlen) {
  std::fseek(s->f, (long)s->end, SEEK_SET);
  uint32_t hdr[2] = {klen, vlen};
  if (std::fwrite(hdr, 1, sizeof hdr, s->f) != sizeof hdr) return -1;
  if (std::fwrite(k, 1, klen, s->f) != klen) return -1;
  uint64_t voff = s->end + sizeof hdr + klen;
  if (vlen != kTombstone && vlen > 0) {
    if (std::fwrite(v, 1, vlen, s->f) != vlen) return -1;
  }
  if (vlen == kTombstone) {
    s->index.erase(std::string(k, klen));
    s->end = voff;
  } else {
    s->index[std::string(k, klen)] = Entry{voff, vlen};
    s->end = voff + vlen;
  }
  return 0;
}

struct ScanIter {
  Store* store;
  std::vector<std::string> keys;
  size_t pos = 0;
};

}  // namespace

extern "C" {

void* kv_open(const char* path) {
  auto* s = new Store();
  s->path = path;
  s->f = std::fopen(path, "r+b");
  if (!s->f) s->f = std::fopen(path, "w+b");
  if (!s->f) {
    delete s;
    return nullptr;
  }
  rebuild_index(s);
  return s;
}

int kv_put(void* h, const char* k, uint32_t klen, const char* v,
           uint32_t vlen) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  return append_record(s, k, klen, v, vlen);
}

// Copy one entry's value bytes into `dst`. Caller holds s->mu. Serves
// from the mmap when available (hash probe + memcpy — the hot path),
// else falls back to fseek+fread.
bool read_value(Store* s, const Entry& e, char* dst) {
  if (ensure_mapped(s) && e.offset + e.length <= s->map_len) {
    std::memcpy(dst, s->map + e.offset, e.length);
    return true;
  }
  std::fseek(s->f, (long)e.offset, SEEK_SET);
  return read_exact(s->f, dst, e.length);
}

// On hit: *out is malloc'd (caller frees via kv_free), returns 0. Miss: -1.
int kv_get(void* h, const char* k, uint32_t klen, char** out,
           uint32_t* out_len) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->index.find(std::string(k, klen));
  if (it == s->index.end()) return -1;
  char* buf = (char*)std::malloc(it->second.length + 1);
  if (!read_value(s, it->second, buf)) {
    std::free(buf);
    return -2;
  }
  buf[it->second.length] = 0;
  *out = buf;
  *out_len = it->second.length;
  return 0;
}

// Batched point lookup — the online store's multi-get hot path. `keys`
// is n records of [u32 klen][key bytes]; the reply is ONE malloc'd
// buffer of n records [u32 vlen][value bytes] in input order, with
// vlen == 0xFFFFFFFF (and no bytes) for a miss. One FFI crossing and
// one lock acquisition amortize over the whole batch — the per-key
// ctypes + mutex cost was most of a native point lookup.
int kv_get_many(void* h, const char* keys, uint32_t nkeys, char** out,
                uint64_t* out_len) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  // Pass 1: resolve entries and size the reply buffer.
  std::vector<const Entry*> hits(nkeys, nullptr);
  uint64_t total = 0;
  const char* p = keys;
  for (uint32_t i = 0; i < nkeys; ++i) {
    uint32_t klen;
    std::memcpy(&klen, p, sizeof klen);
    p += sizeof klen;
    auto it = s->index.find(std::string(p, klen));
    p += klen;
    total += sizeof(uint32_t);
    if (it != s->index.end()) {
      hits[i] = &it->second;
      total += it->second.length;
    }
  }
  char* buf = (char*)std::malloc(total ? total : 1);
  if (!buf) return -1;
  char* w = buf;
  for (uint32_t i = 0; i < nkeys; ++i) {
    if (!hits[i]) {
      uint32_t miss = kTombstone;
      std::memcpy(w, &miss, sizeof miss);
      w += sizeof miss;
      continue;
    }
    uint32_t vlen = hits[i]->length;
    std::memcpy(w, &vlen, sizeof vlen);
    w += sizeof vlen;
    if (!read_value(s, *hits[i], w)) {
      std::free(buf);
      return -2;
    }
    w += vlen;
  }
  *out = buf;
  *out_len = total;
  return 0;
}

int kv_delete(void* h, const char* k, uint32_t klen) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  return append_record(s, k, klen, nullptr, kTombstone);
}

uint64_t kv_count(void* h) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  return s->index.size();
}

void kv_flush(void* h) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  std::fflush(s->f);
  s->flushed = s->end;
}

// Rewrite the log with live records only; returns reclaimed bytes.
int64_t kv_compact(void* h) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  std::string tmp_path = s->path + ".compact";
  std::FILE* tmp = std::fopen(tmp_path.c_str(), "w+b");
  if (!tmp) return -1;
  uint64_t old_end = s->end, pos = 0;
  std::unordered_map<std::string, Entry> new_index;
  std::vector<char> vbuf;
  for (auto& [key, e] : s->index) {
    vbuf.resize(e.length);
    std::fseek(s->f, (long)e.offset, SEEK_SET);
    if (!read_exact(s->f, vbuf.data(), e.length)) continue;
    uint32_t hdr[2] = {(uint32_t)key.size(), e.length};
    std::fwrite(hdr, 1, sizeof hdr, tmp);
    std::fwrite(key.data(), 1, key.size(), tmp);
    std::fwrite(vbuf.data(), 1, e.length, tmp);
    uint64_t voff = pos + sizeof hdr + key.size();
    new_index[key] = Entry{voff, e.length};
    pos = voff + e.length;
  }
  std::fflush(tmp);
  drop_mapping(s);  // the old file is about to be replaced
  std::fclose(s->f);
  if (std::rename(tmp_path.c_str(), s->path.c_str()) != 0) {
    std::fclose(tmp);
    s->f = std::fopen(s->path.c_str(), "r+b");
    s->flushed = 0;  // conservatively re-flush before the next mapping
    return -1;
  }
  s->f = tmp;
  s->index = std::move(new_index);
  s->end = pos;
  s->flushed = pos;
  return (int64_t)(old_end - pos);
}

void* kv_scan(void* h) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  auto* it = new ScanIter();
  it->store = s;
  it->keys.reserve(s->index.size());
  for (auto& [key, _] : s->index) it->keys.push_back(key);
  return it;
}

int kv_scan_next(void* iter, char** out, uint32_t* out_len) {
  auto* it = static_cast<ScanIter*>(iter);
  while (it->pos < it->keys.size()) {
    const std::string& key = it->keys[it->pos++];
    int rc = kv_get(it->store, key.data(), (uint32_t)key.size(), out, out_len);
    if (rc == 0) return 0;  // key may have been deleted since snapshot
  }
  return -1;
}

void kv_scan_close(void* iter) { delete static_cast<ScanIter*>(iter); }

void kv_free(char* p) { std::free(p); }

void kv_close(void* h) {
  auto* s = static_cast<Store*>(h);
  {
    std::lock_guard<std::mutex> lock(s->mu);
    drop_mapping(s);
    std::fflush(s->f);
    std::fclose(s->f);
  }
  delete s;
}

}  // extern "C"
