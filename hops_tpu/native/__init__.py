"""Native (C++) runtime components.

The reference's native substrate (libhdfs storage driver, MySQL-NDB
online store, JVM/Spark runtime) lived outside the repo (SURVEY.md §2,
"implied native"). The TPU build ships its own: C++ engines compiled to
a shared library (``libhops_native.so``) reached via ``ctypes`` — no
pybind11 dependency. Each binding degrades to a pure-Python fallback
when the library hasn't been built, so the framework works everywhere
and goes fast where it matters.

Build: ``make -C hops_tpu/native`` (or ``python -m hops_tpu.native.build``).
"""

from __future__ import annotations

import ctypes
import os
from pathlib import Path

_LIB_NAME = "libhops_native.so"
_lib: ctypes.CDLL | None = None


def lib_path() -> Path:
    return Path(__file__).parent / _LIB_NAME


def load() -> ctypes.CDLL | None:
    """Load the native library; None if not built/loadable.

    Only successful loads are cached: a missing library is re-checked on
    the next call, so building ``libhops_native.so`` mid-process (as the
    test suite does) takes effect without an interpreter restart.
    """
    global _lib
    if _lib is not None:
        return _lib
    if os.environ.get("HOPS_TPU_DISABLE_NATIVE"):
        return None
    p = lib_path()
    if p.exists():
        try:
            _lib = ctypes.CDLL(str(p))
        except OSError:
            _lib = None
    return _lib


def available() -> bool:
    return load() is not None
