"""Zero-downtime versioned rollouts: warm, canary-gate, shift, drain.

The platform move the reference does with K8s rolling updates, done
natively over the replica manager + router:

1. **Warm** one new-version replica and gate on its ``/healthz`` —
   model load and jit warmup happen OFF the serving path.
2. **Canary**: the warmed replica joins the routable set (least-loaded
   selection naturally sends it traffic — it is the idlest replica in
   the fleet) and is judged over up to ``canary_requests`` forwards
   inside ``canary_window_s``. The router's per-replica circuit breaker
   is the judge: if the new version's error rate trips it open, the
   canary is reaped and the fleet ROLLS BACK to the prior version —
   clients only ever saw retried requests, never a failed one (replica
   5xx retries on an old replica). The gate judges whatever traffic
   arrives: an idle fleet's window passes vacuously (rollouts must not
   require synthetic traffic) — logged, with ``canary_forwards`` in
   the summary.
3. **Shift + drain**: one old replica at a time — spawn its new-version
   replacement, wait ready, then drain the old one (503-draining
   contract; in-flight work finishes) and reap it at in-flight zero.
   The ready count never dips below the starting count, so there is no
   request window with zero (or even reduced) capacity.

Outcomes land on ``hops_tpu_fleet_rollouts_total{outcome}`` and the
returned summary; a rollback raises nothing — it IS the designed
recovery path.
"""

from __future__ import annotations

import time
from typing import Any

from hops_tpu.modelrepo.fleet.replicas import FleetSpawnError
from hops_tpu.runtime import flight
from hops_tpu.runtime.logging import get_logger
from hops_tpu.telemetry.metrics import REGISTRY

log = get_logger(__name__)

_m_rollouts = REGISTRY.counter(
    "hops_tpu_fleet_rollouts_total",
    "Version rollouts per fleet endpoint and outcome "
    "(completed | rolled_back | spawn_failed)",
    labels=("model", "outcome"),
)


class RolloutError(RuntimeError):
    """A rollout failed for a reason rollback cannot fix (e.g. the new
    version cannot spawn at all)."""


def roll_out(
    manager: Any,
    router: Any,
    version: int | None,
    *,
    canary_requests: int = 8,
    canary_window_s: float = 15.0,
    drain_timeout_s: float = 30.0,
    poll_interval_s: float = 0.02,
) -> dict[str, Any]:
    """Roll the fleet behind ``router`` onto ``version``.

    Returns a summary dict: ``outcome`` (``completed`` |
    ``rolled_back``), ``version``, ``replaced`` (old rids reaped),
    ``canary`` (its rid), ``duration_s``. Raises :class:`RolloutError`
    only when the new version cannot even spawn its canary.
    """
    t0 = time.monotonic()
    olds = [r.rid for r in manager.ready()]
    name = manager.name
    if not olds:
        raise RolloutError(f"fleet {name!r} has no ready replicas to roll")
    log.info("fleet %s: rolling %d replica(s) to version %s",
             name, len(olds), version)

    # 1. Warm the canary (readiness-gated inside spawn()).
    try:
        canary = manager.spawn(version)
    except FleetSpawnError as e:
        _m_rollouts.inc(model=name, outcome="spawn_failed")
        flight.record("rollout", model=name, outcome="spawn_failed")
        raise RolloutError(
            f"fleet {name!r}: version {version} failed to warm a canary: {e}"
        ) from e

    # 2. Canary gate: survive traffic, judged by its breaker. The gate
    # judges whatever traffic ARRIVES in the window — an idle fleet's
    # canary passes vacuously (by design: rollouts must not require
    # synthetic traffic), but that is logged and surfaced as
    # canary_forwards in the summary so operators can see how much
    # validation the new version actually got.
    forwarded0 = _forwards(name, canary.rid)
    deadline = time.monotonic() + canary_window_s
    tripped = False
    while time.monotonic() < deadline:
        if router.breaker_state(canary.rid) == "open":
            tripped = True
            break
        if _forwards(name, canary.rid) - forwarded0 >= canary_requests:
            break
        time.sleep(poll_interval_s)
    # The breaker may trip on the very last judged request.
    tripped = tripped or router.breaker_state(canary.rid) == "open"
    canary_forwards = int(_forwards(name, canary.rid) - forwarded0)
    if not tripped and canary_forwards < canary_requests:
        log.warning(
            "fleet %s: canary %s saw only %d/%d requests in its %.1fs "
            "window — version %s rolls out with that much validation",
            name, canary.rid, canary_forwards, canary_requests,
            canary_window_s, version)
    if tripped:
        log.warning("fleet %s: canary %s (version %s) tripped its breaker — "
                    "rolling back", name, canary.rid, version)
        _drain_and_reap(manager, canary.rid, drain_timeout_s, poll_interval_s)
        _m_rollouts.inc(model=name, outcome="rolled_back")
        flight.record("rollout", model=name, outcome="rolled_back")
        return {
            "outcome": "rolled_back",
            "version": version,
            "canary": canary.rid,
            "replaced": [],
            "duration_s": round(time.monotonic() - t0, 3),
        }

    # 3. Shift: replace old replicas one at a time, capacity-neutral.
    # The judged version is committed into the serving definition
    # FIRST: a concurrent autoscaler spawn (heal or scale-up) from
    # here on resolves the NEW artifact instead of quietly
    # resurrecting the old one — the straggler sweep below catches the
    # spawns that raced the commit. The canary already added one new
    # replica, so the FIRST old drains without a fresh spawn; every
    # further old gets its replacement warmed before the drain starts.
    manager.commit_version(version)
    target = canary.version
    replaced: list[str] = []
    new_rids = [canary.rid]
    for i, old in enumerate(olds):
        if i > 0:
            try:
                new_rids.append(manager.spawn(version).rid)
            except FleetSpawnError as e:
                # Capacity-safe abort: olds not yet drained keep
                # serving the OLD version; the already-landed new
                # replicas serve the new one. Operators see a mixed
                # fleet on /fleet and a rolled_back outcome — but the
                # committed definition is the judged NEW version, so
                # autoscaler heals converge the fleet forward.
                log.warning("fleet %s: replacement spawn failed mid-rollout "
                            "(%s); aborting with %d/%d replaced",
                            name, e, len(replaced), len(olds))
                _m_rollouts.inc(model=name, outcome="rolled_back")
                flight.record("rollout", model=name, outcome="rolled_back")
                return {
                    "outcome": "rolled_back",
                    "version": version,
                    "canary": canary.rid,
                    "replaced": replaced,
                    "duration_s": round(time.monotonic() - t0, 3),
                }
        _drain_and_reap(manager, old, drain_timeout_s, poll_interval_s)
        replaced.append(old)
    # Straggler sweep: an autoscaler spawn that read the definition
    # BEFORE the commit hosts the old version and is not in the
    # starting snapshot — without this it survives a "completed"
    # rollout and the fleet serves mixed versions indefinitely.
    # Stragglers drain WITHOUT a replacement (they were capacity the
    # autoscaler added; it re-heals with the new version if the fleet
    # is genuinely below floor). Version-None rollouts change nothing,
    # so there is nothing to sweep.
    if version is not None:
        sweep_deadline = time.monotonic() + drain_timeout_s
        while time.monotonic() < sweep_deadline:
            stragglers = [r.rid for r in manager.ready()
                          if r.version != target]
            for rid in stragglers:
                log.warning(
                    "fleet %s: draining old-version straggler %s "
                    "(spawned mid-rollout)", name, rid)
                _drain_and_reap(manager, rid, drain_timeout_s,
                                poll_interval_s)
                replaced.append(rid)
            # A spawn still warming may host either version (its
            # config read may predate the commit): wait for it to
            # settle rather than declare the fleet homogeneous.
            pending = [r for r in manager.replicas()
                       if r.state == "starting"
                       and (r.version is None or r.version != target)]
            if not stragglers and not pending:
                break
            if not stragglers:
                time.sleep(poll_interval_s)
    _m_rollouts.inc(model=name, outcome="completed")
    flight.record("rollout", model=name, outcome="completed")
    log.info("fleet %s: rollout to version %s complete (%d replaced, %.2fs)",
             name, version, len(replaced), time.monotonic() - t0)
    return {
        "outcome": "completed",
        "version": version,
        "canary": canary.rid,
        "canary_forwards": canary_forwards,
        "replaced": replaced,
        "new_replicas": new_rids,
        "duration_s": round(time.monotonic() - t0, 3),
    }


def _drain_and_reap(manager: Any, rid: str, timeout_s: float,
                    poll_s: float) -> None:
    """Stop admissions on ``rid``, wait for in-flight zero, reap. A
    drain that outlives ``timeout_s`` is force-reaped (logged) — a
    wedged request must not wedge the rollout."""
    manager.drain(rid)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if manager.drained(rid):
            manager.reap(rid)
            return
        time.sleep(poll_s)
    log.warning("fleet %s: replica %s still has in-flight work after "
                "%.1fs drain; force-reaping", manager.name, rid, timeout_s)
    manager.reap(rid)


def _forwards(model: str, rid: str) -> float:
    """Router-side forwards to ``rid`` (``value()`` auto-creates the
    label child, so an untouched replica reads 0)."""
    from hops_tpu.modelrepo.fleet.router import _m_forwards

    return _m_forwards.value(model=model, replica=rid)
