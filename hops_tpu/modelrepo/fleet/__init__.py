"""Horizontal serving fleet: N replicas behind one routed endpoint.

PAPER.md's L4 layer (Hopsworks model serving on Docker/K8s) is a fleet
of serving containers behind a platform endpoint. This package is that
layer natively: a least-loaded front router
(:mod:`~hops_tpu.modelrepo.fleet.router`), a replica manager spawning
``serving_host --fleet-worker`` processes
(:mod:`~hops_tpu.modelrepo.fleet.replicas`), telemetry-driven
autoscaling (:mod:`~hops_tpu.modelrepo.fleet.autoscale`) and
zero-downtime versioned rollouts
(:mod:`~hops_tpu.modelrepo.fleet.rollout`). One call stands it up::

    from hops_tpu.modelrepo import fleet, serving

    serving.create_or_update("mnist", model_name="mnist")
    f = fleet.start_fleet(
        "mnist", replicas=3,
        autoscale=fleet.AutoscalePolicy(min_replicas=2, max_replicas=6),
        rate_limits={"default": {"rate_rps": 200, "burst": 50}},
    )
    f.predict([[...]])            # POST {endpoint}/predict
    f.roll_out(version=2)         # warm → canary → shift → drain
    f.stop()

See docs/operations.md "Serving fleet" for the routing policy, the
autoscaler knobs, the rollout/rollback runbook and every
``hops_tpu_fleet_*`` metric.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Any

from hops_tpu.modelrepo.fleet.autoscale import Autoscaler, AutoscalePolicy
from hops_tpu.modelrepo.fleet.replicas import (
    FleetSpawnError,
    Replica,
    ReplicaManager,
)
from hops_tpu.modelrepo.fleet.rollout import RolloutError, roll_out
from hops_tpu.modelrepo.fleet.router import (
    EjectionPolicy,
    HedgePolicy,
    Router,
    TenantRateLimiter,
    TokenBucket,
)

__all__ = [
    "Autoscaler",
    "AutoscalePolicy",
    "EjectionPolicy",
    "FleetSpawnError",
    "HedgePolicy",
    "Replica",
    "ReplicaManager",
    "RolloutError",
    "Router",
    "ServingFleet",
    "TenantRateLimiter",
    "TokenBucket",
    "roll_out",
    "start_fleet",
]


#: Soak-proven default SLO the default brownout controller defends —
#: deliberately loose (an interactive p99 of 1s) so it only fires on a
#: genuinely burning fleet, never on CI jitter. Pass an explicit
#: ``brownout=qos.BrownoutPolicy(...)`` to tighten it.
DEFAULT_BROWNOUT_SLO_P99_MS = 1000.0


class ServingFleet:
    """Manager + router + (optional) autoscaler as one handle.

    Gray-failure tolerance is ON by default: unless the caller says
    otherwise, the router runs adaptive hedging (:class:`HedgePolicy`),
    outlier ejection (:class:`EjectionPolicy`), and brownout degradation
    (``qos.BrownoutPolicy`` at :data:`DEFAULT_BROWNOUT_SLO_P99_MS`) —
    the PR 14 soak configuration. An explicit ``hedge=None`` /
    ``ejection=None`` / ``brownout=None`` opts that mechanism out (the
    router maps ``None`` to its disabled policy)."""

    def __init__(
        self,
        name: str,
        replicas: int = 2,
        *,
        inprocess: bool = False,
        autoscale: AutoscalePolicy | None = None,
        autoscale_interval_s: float = 1.0,
        rate_limits: dict[str, dict[str, float]] | None = None,
        spawn_timeout_s: float = 60.0,
        placement: Any = None,
        **router_kwargs: Any,
    ):
        from hops_tpu.runtime import qos

        # setdefault, not a default argument: an EXPLICIT None must
        # survive to the Router (which maps it to the disabled policy)
        # while an omitted kwarg gets the soak default.
        router_kwargs.setdefault("hedge", HedgePolicy())
        router_kwargs.setdefault("ejection", EjectionPolicy())
        router_kwargs.setdefault(
            "brownout",
            qos.BrownoutPolicy(slo_p99_ms=DEFAULT_BROWNOUT_SLO_P99_MS))
        self.manager = ReplicaManager(
            name, inprocess=inprocess, spawn_timeout_s=spawn_timeout_s,
            placement=placement)
        self.router = None
        self.autoscaler = None
        try:
            for _ in range(replicas):
                self.manager.spawn()
            self.router = Router(
                self.manager, rate_limits=rate_limits, **router_kwargs)
            if autoscale is not None:
                self.autoscaler = Autoscaler(
                    self.manager, self.router, autoscale,
                ).start(autoscale_interval_s)
        except BaseException:
            # A failed startup must not leak already-spawned workers:
            # the caller never gets a handle to stop() them.
            if self.router is not None:
                self.router.stop()
            self.manager.stop()
            raise

    @property
    def endpoint(self) -> str:
        return self.router.endpoint

    def predict(self, instances: list[Any], *, tenant: str | None = None,
                priority: str | None = None,
                timeout_s: float = 30.0) -> dict[str, Any]:
        """POST ``/predict`` through the router (convenience client)."""
        headers = {"Content-Type": "application/json"}
        if tenant is not None:
            headers["X-Tenant"] = tenant
        if priority is not None:
            headers["X-Priority"] = priority
        req = urllib.request.Request(
            f"{self.endpoint}/predict",
            data=json.dumps({"instances": instances}).encode(),
            headers=headers,
        )
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return json.loads(resp.read())

    def roll_out(self, version: int | None, **kwargs: Any) -> dict[str, Any]:
        return roll_out(self.manager, self.router, version, **kwargs)

    def describe(self) -> dict[str, Any]:
        return self.router.describe()

    def stop(self) -> None:
        if self.autoscaler is not None:
            self.autoscaler.stop()
        self.router.stop()
        self.manager.stop()

    def __enter__(self) -> "ServingFleet":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.stop()


def start_fleet(name: str, replicas: int = 2, **kwargs: Any) -> ServingFleet:
    """Stand up a fleet for an existing ``serving.create_or_update``
    endpoint definition: spawn ``replicas`` workers, start the router
    (and the autoscaler when a policy is given)."""
    return ServingFleet(name, replicas, **kwargs)
