"""Telemetry-driven replica autoscaling with hysteresis and cooldowns.

The scaling signal is the router's view of the fleet (it already
scrapes every replica): ``Router.fleet_load()`` — mean routing score
(in-flight + queue depth + shed rate) per ready replica — plus an
optional p99-latency trigger from the router's rolling window. Policy:

- scale UP when load stays above ``target_load * high_factor`` (or p99
  above ``p99_target_ms``) for ``breaches_to_scale`` consecutive ticks
  and the up-cooldown has elapsed — hysteresis on both axes, so one
  bursty tick doesn't flap the fleet;
- scale DOWN when load stays below ``target_load * low_factor`` just as
  persistently: the least-loaded replica is DRAINED (it finishes its
  in-flight work behind the 503-draining contract), and a later tick
  reaps it once its in-flight count hits zero — capacity never
  disappears under a request;
- ``min_replicas``/``max_replicas`` clamp everything, and a fleet that
  has fallen BELOW ``min_replicas`` (chaos kill, failed spawn) is
  healed back up regardless of load.

Every decision lands on ``hops_tpu_fleet_target_replicas`` (gauge) and
``hops_tpu_fleet_scale_events_total{direction}`` — the dashboard trace
of why the fleet is the size it is. ``tick()`` is synchronous and
deterministic under an injected clock; ``start()`` wraps it in a
daemon-thread loop for production use.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable

from hops_tpu.runtime.logging import get_logger
from hops_tpu.telemetry.metrics import REGISTRY

log = get_logger(__name__)

_m_target = REGISTRY.gauge(
    "hops_tpu_fleet_target_replicas",
    "Autoscaler's current target replica count per fleet endpoint",
    labels=("model",),
)
_m_scale_events = REGISTRY.counter(
    "hops_tpu_fleet_scale_events_total",
    "Autoscaler decisions per fleet endpoint and direction (up | down)",
    labels=("model", "direction"),
)


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """Knobs (docs/operations.md "Serving fleet")."""

    min_replicas: int = 1
    max_replicas: int = 4
    #: Healthy per-replica routing score (inflight + queue + shed rate).
    target_load: float = 4.0
    #: Load above target*high_factor is a scale-up breach; below
    #: target*low_factor a scale-down breach — the hysteresis band.
    high_factor: float = 1.25
    low_factor: float = 0.5
    #: Consecutive breaching ticks before acting (flap damping).
    breaches_to_scale: int = 2
    up_cooldown_s: float = 5.0
    down_cooldown_s: float = 15.0
    #: Optional latency trigger: scale up when the router's p99 exceeds
    #: this (None = load-only). The signal is SLO-driven: the router's
    #: windowed estimate from the ``hops_tpu_fleet_latency_seconds``
    #: histogram (``Router.histogram_p99_ms``), falling back to the
    #: rolling-window ``recent_p99_ms`` until enough bucket data lands.
    p99_target_ms: float | None = None
    #: An active brownout (the router's SLO-burn controller at level
    #: >= 1) counts as an up-breach: sustained burn means the fleet is
    #: under-provisioned, and capacity is the durable fix brownout is
    #: buying time for.
    scale_on_brownout: bool = True

    def __post_init__(self) -> None:
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if self.low_factor >= self.high_factor:
            raise ValueError("low_factor must be < high_factor (hysteresis)")


class Autoscaler:
    """Drives a :class:`ReplicaManager` from a :class:`Router`'s
    telemetry under an :class:`AutoscalePolicy`."""

    def __init__(
        self,
        manager: Any,
        router: Any,
        policy: AutoscalePolicy | None = None,
        *,
        clock: Callable[[], float] = time.monotonic,
        load_fn: Callable[[], float | None] | None = None,
    ):
        self.manager = manager
        self.router = router
        self.policy = policy or AutoscalePolicy()
        self._clock = clock
        self._load_fn = load_fn or router.fleet_load
        self._up_breaches = 0
        self._down_breaches = 0
        self._last_up = -float("inf")
        self._last_down = -float("inf")
        self.target = max(self.policy.min_replicas, len(manager.ready()) or 0)
        self._m_target = _m_target.labels(model=manager.name)
        self._m_target.set(self.target)
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # -- the decision loop ----------------------------------------------------

    def tick(self) -> str | None:
        """One evaluation: reconcile placed liveness, reap finished
        drains, heal below-minimum, then judge load. Returns the action
        taken (``"up"`` | ``"down"`` | ``"reap"`` | ``"heal"`` | None)
        — tests drive this directly."""
        # Placed fleets first sweep for replicas whose HOST died (no
        # local SIGCHLD): reconcile marks them failed, which drops the
        # live count below target and turns this very tick into a heal
        # — re-placement lands on the surviving hosts.
        reconcile = getattr(self.manager, "reconcile", None)
        if reconcile is not None:
            reconcile()
        self._reap_drained()
        live = [r for r in self.manager.replicas()
                if r.state in ("ready", "starting")]
        now = self._clock()
        # Healing beats load math: a fleet below its floor serves the
        # next burst badly no matter what the gauges say right now.
        if len(live) < max(self.policy.min_replicas, min(self.target, self.policy.max_replicas)):
            self._spawn_one()
            return "heal"
        load = self._load_fn()
        p99 = self._p99_ms()
        up_breach = False
        if load is not None and load > self.policy.target_load * self.policy.high_factor:
            up_breach = True
        if (self.policy.p99_target_ms is not None and p99 is not None
                and p99 > self.policy.p99_target_ms):
            up_breach = True
        if (self.policy.scale_on_brownout
                and getattr(self.router, "brownout_level", 0) >= 1):
            up_breach = True
        down_breach = (
            load is not None
            and load < self.policy.target_load * self.policy.low_factor
        )
        self._up_breaches = self._up_breaches + 1 if up_breach else 0
        self._down_breaches = self._down_breaches + 1 if down_breach else 0

        ready = len(self.manager.ready())
        if (self._up_breaches >= self.policy.breaches_to_scale
                and ready < self.policy.max_replicas
                and now - self._last_up >= self.policy.up_cooldown_s):
            self.target = min(self.policy.max_replicas, ready + 1)
            self._last_up = now
            self._up_breaches = 0
            self._m_target.set(self.target)
            _m_scale_events.inc(model=self.manager.name, direction="up")
            log.info("fleet %s: scaling UP to %d (load=%.2f p99=%s)",
                     self.manager.name, self.target, load or -1, p99)
            self._spawn_one()
            return "up"
        if (self._down_breaches >= self.policy.breaches_to_scale
                and ready > self.policy.min_replicas
                and now - self._last_down >= self.policy.down_cooldown_s):
            self.target = max(self.policy.min_replicas, ready - 1)
            self._last_down = now
            self._down_breaches = 0
            self._m_target.set(self.target)
            _m_scale_events.inc(model=self.manager.name, direction="down")
            victim = self._least_loaded_ready()
            if victim is not None:
                log.info("fleet %s: scaling DOWN to %d — draining %s "
                         "(load=%.2f)", self.manager.name, self.target,
                         victim.rid, load or -1)
                self.manager.drain(victim.rid)
            return "down"
        return None

    def _p99_ms(self) -> float | None:
        """The latency trigger's signal: the router's histogram-derived
        windowed p99 when available (SLO truth from bucket deltas),
        else its rolling window. Tolerates routers without the
        histogram surface (tests drive stubs)."""
        if self.router is None:
            return None
        hist = getattr(self.router, "histogram_p99_ms", None)
        p99 = hist() if hist is not None else None
        if p99 is None:
            p99 = self.router.recent_p99_ms()
        return p99

    def _reap_drained(self) -> str | None:
        for rep in self.manager.replicas():
            if rep.state == "draining" and self.manager.drained(rep.rid):
                self.manager.reap(rep.rid)
                return "reap"
        return None

    def _spawn_one(self) -> None:
        try:
            self.manager.spawn()
        except Exception as e:  # noqa: BLE001 — next tick retries
            log.warning("fleet %s: autoscale spawn failed (%s: %s); "
                        "next tick retries", self.manager.name,
                        type(e).__name__, e)

    def _least_loaded_ready(self) -> Any | None:
        ready = self.manager.ready()
        if not ready:
            return None
        if self.router is None:
            return ready[-1]
        return min(ready, key=lambda r: self.router._view(r.rid).score())

    # -- the daemon loop ------------------------------------------------------

    def start(self, interval_s: float = 1.0) -> "Autoscaler":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, args=(interval_s,), daemon=True,
                name=f"fleet-autoscaler-{self.manager.name}",
            )
            self._thread.start()
        return self

    def _loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the loop must survive
                log.exception("fleet %s: autoscaler tick failed",
                              self.manager.name)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
