"""Replica lifecycle: spawn, readiness-gate, drain, reap N serving workers.

The reference platform's serving tier is a FLEET of containers behind
one endpoint (PAPER.md L4: Docker/K8s model serving) — capacity is a
replica count, not a process. This module owns that count: each replica
is one ``serving._RunningServing`` of the SAME endpoint config on its
own private port, hosted either

- **out of process** (default): a detached
  ``python -m hops_tpu.modelrepo.serving_host --fleet-worker <dir>``
  worker per replica — its own interpreter, its own telemetry registry
  (so the router's per-replica ``/metrics.json`` scrape sees truly
  per-replica load), surviving the manager's death; or
- **in process** (``inprocess=True``): a server thread per replica —
  the fast path for tests, benches and chaos drills (replicas share the
  process registry, so per-replica load comes from the router's own
  inflight accounting rather than the scrape).

Replica state machine: ``starting -> ready -> draining -> stopped``
(``failed`` from anywhere). ``drain()`` flips the replica's own
``/healthz`` to the 503 ``draining`` contract (serving.py) so the
router stops routing there without any side channel; ``reap()`` then
terminates it. The ``fleet.spawn`` fault point fires before every
spawn so chaos tests can fail replica creation deterministically.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path
from typing import Any

from hops_tpu.modelrepo import serving
from hops_tpu.runtime import faultinject, flight, fs
from hops_tpu.runtime.httpclient import HTTPPool
from hops_tpu.runtime.logging import get_logger
from hops_tpu.telemetry.metrics import REGISTRY

log = get_logger(__name__)

#: Replica lifecycle states (the ``hops_tpu_fleet_replicas`` gauge is
#: labelled by these).
STATES = ("starting", "ready", "draining", "stopped", "failed")

_m_replicas = REGISTRY.gauge(
    "hops_tpu_fleet_replicas",
    "Replica count per fleet endpoint and lifecycle state",
    labels=("model", "state"),
)


class FleetSpawnError(RuntimeError):
    """A replica failed to spawn or come ready in time."""


@dataclasses.dataclass
class Replica:
    """One serving worker of the fleet (process-, thread-, or
    placement-hosted). ``host`` is where its serving port lives —
    loopback for local workers, the placing host's address for placed
    ones; the router forwards to ``host:port`` either way."""

    rid: str
    version: int | None
    state: str = "starting"
    host: str = "127.0.0.1"
    port: int | None = None
    proc: subprocess.Popen | None = None
    server: Any = None  # in-process serving._RunningServing
    unit: Any = None  # placement.PlacedUnit for placed replicas
    spawned_at: float = 0.0

    @property
    def pid(self) -> int | None:
        if self.proc is not None:
            return self.proc.pid
        return self.unit.pid if self.unit is not None else None


class ReplicaManager:
    """Spawns and reaps the serving workers behind one fleet endpoint.

    ``name`` must be an existing ``serving.create_or_update`` endpoint
    definition; every replica hosts that config (optionally pinned to a
    different ``version`` — the rollout path). Thread-safe: the router,
    the autoscaler and a rollout all mutate the same fleet.
    """

    def __init__(self, name: str, *, inprocess: bool = False,
                 spawn_timeout_s: float = 60.0, placement: Any = None):
        reg = serving._load_registry()
        if name not in reg:
            raise KeyError(f"serving {name!r} not found — create_or_update first")
        if placement is not None and inprocess:
            raise ValueError("placement= and inprocess=True are exclusive: "
                             "a placed replica lives on its host's agent")
        self.name = name
        self.inprocess = inprocess
        #: A ``jobs.placement.PlacementClient``: replicas spawn on the
        #: registry's hosts via their hostd agents instead of local
        #: ``Popen`` — the autoscaler and rollouts ride through
        #: unchanged, they only ever call spawn/drain/reap here.
        self.placement = placement
        self.spawn_timeout_s = spawn_timeout_s
        self._lock = threading.Lock()
        self._replicas: dict[str, Replica] = {}  # guarded by: self._lock
        self._counter = 0  # guarded by: self._lock
        self._closed = False  # guarded by: self._lock
        #: Units whose slot was re-placed while their host was
        #: unreachable (generation already bumped): kept so the
        #: reconcile sweep can reap the zombie once the partition
        #: heals instead of leaking the worker forever.
        self._superseded: list[Any] = []  # guarded by: self._lock
        # Probes and drains go through a pool rather than raw urllib so
        # the transport.send fault seam covers them: a partitioned host
        # must look unreachable to the liveness sweep, not just to the
        # router's forwards.
        self._probe_pool = HTTPPool(max_idle_per_host=2, identity="fleet")
        self._publish_states()

    # -- bookkeeping ----------------------------------------------------------

    def _publish_states(self) -> None:
        with self._lock:
            reps = list(self._replicas.values())
        for state in STATES:
            _m_replicas.set(
                sum(1 for r in reps if r.state == state),
                model=self.name, state=state,
            )

    def replicas(self) -> list[Replica]:
        """Snapshot of all live (non-stopped) replicas."""
        with self._lock:
            return [r for r in self._replicas.values()
                    if r.state not in ("stopped", "failed")]

    def _forget(self, rid: str) -> None:
        """Drop a dead replica's record. Every rollout and autoscale
        churn mints a fresh rid, so retaining stopped/failed entries
        (each holding a Popen) grows ``_replicas`` — and every
        ``_publish_states`` pass over it — for the manager's lifetime;
        the router prunes its per-rid views for the same reason."""
        with self._lock:
            self._replicas.pop(rid, None)

    def ready(self) -> list[Replica]:
        return [r for r in self.replicas() if r.state == "ready"]

    def get(self, rid: str) -> Replica | None:
        with self._lock:
            return self._replicas.get(rid)

    def _fleet_dir(self) -> Path:
        p = Path(fs.project_path("Serving")) / f"{self.name}.fleet"
        p.mkdir(parents=True, exist_ok=True)
        return p

    def _replica_cfg(self, version: int | None) -> dict[str, Any]:
        """The serving config a replica hosts: the endpoint's current
        definition, re-resolved to ``version``'s artifact when pinned
        (the rollout path — old and new replicas differ only here)."""
        cfg = dict(serving._load_registry()[self.name])
        cfg.pop("port", None)
        cfg.pop("pid", None)
        if version is not None and version != cfg.get("model_version"):
            from hops_tpu.modelrepo import registry

            # The model registry is keyed by MODEL name, which differs
            # from the endpoint name whenever the definition was
            # created with model_name=. Pre-model_name records fall
            # back to the endpoint name (they could only have been
            # created with name == model_name).
            meta = registry.get_model(
                cfg.get("model_name") or self.name, version)
            cfg["artifact_path"] = meta["path"]
            cfg["model_version"] = meta["version"]
        return cfg

    # -- spawn ----------------------------------------------------------------

    def spawn(self, version: int | None = None, *,
              wait_ready: bool = True) -> Replica:
        """Spawn one replica (pinned to ``version`` when given) and —
        by default — gate on its ``/healthz`` answering ready. Raises
        :class:`FleetSpawnError` on spawn or readiness failure; the
        caller's retry policy owns recovery (``fleet.spawn`` faults
        land here)."""
        with self._lock:
            if self._closed:
                raise FleetSpawnError(
                    f"fleet {self.name!r} manager is stopped")
            rid = f"r{self._counter}"
            self._counter += 1
            rep = Replica(rid=rid, version=version, spawned_at=time.monotonic())
            self._replicas[rid] = rep
        try:
            faultinject.fire("fleet.spawn")  # chaos point
            cfg = self._replica_cfg(version)
            rep.version = cfg.get("model_version")
            if self.placement is not None:
                self._spawn_placed(rep, cfg)
            elif self.inprocess:
                rep.server = serving._RunningServing(cfg)
                rep.port = rep.server.port
            else:
                self._spawn_process(rep, cfg)
            if wait_ready:
                # Via the local rep, not the rid: a stop() racing this
                # spawn may already have swept the rid out of the book.
                self._wait_ready(rep)
            else:
                rep.state = "ready" if self.inprocess else rep.state
        except Exception as e:
            self._teardown(rep)
            rep.state = "failed"
            self._forget(rid)
            self._publish_states()
            if not isinstance(e, FleetSpawnError):
                raise FleetSpawnError(
                    f"replica {rid} of {self.name!r} failed to spawn: "
                    f"{type(e).__name__}: {e}"
                ) from e
            raise
        with self._lock:
            closed = self._closed
        if closed:
            # stop() ran while this spawn was in flight (e.g. a blocked
            # autoscaler tick): its reap sweep may have missed a worker
            # process that only just announced. Tear the LOCAL rep down
            # (not reap-by-rid: the sweep may have already reaped and
            # forgotten this rid before the Popen existed, so the book
            # lookup would no-op and leak the worker) so nothing
            # outlives the fleet.
            self._teardown(rep)
            rep.state = "stopped"
            self._forget(rid)
            self._publish_states()
            raise FleetSpawnError(
                f"fleet {self.name!r} manager stopped during spawn of {rid}")
        self._publish_states()
        log.info("fleet %s: replica %s up on port %s (version %s)",
                 self.name, rep.rid, rep.port, rep.version)
        return rep

    def _spawn_process(self, rep: Replica, cfg: dict[str, Any]) -> None:
        rdir = self._fleet_dir() / rep.rid
        rdir.mkdir(parents=True, exist_ok=True)
        (rdir / "state.json").unlink(missing_ok=True)
        (rdir / "cfg.json").write_text(json.dumps(cfg, indent=2, default=str))
        from hops_tpu.jobs.api import _child_pythonpath

        env = dict(os.environ)
        env["HOPS_TPU_WORKSPACE"] = str(fs.workspace_root())
        env["HOPS_TPU_PROJECT"] = fs.project_name()
        env["PYTHONPATH"] = _child_pythonpath(env.get("PYTHONPATH"))
        with open(rdir / "worker.log", "a") as logfile:
            rep.proc = subprocess.Popen(
                [sys.executable, "-m", "hops_tpu.modelrepo.serving_host",
                 "--fleet-worker", str(rdir)],
                stdout=logfile, stderr=subprocess.STDOUT, env=env,
                start_new_session=True,
            )
        deadline = time.monotonic() + self.spawn_timeout_s
        state_file = rdir / "state.json"
        poll = 0.05
        while time.monotonic() < deadline:
            if state_file.exists():
                state = json.loads(state_file.read_text())
                if state.get("pid") == rep.proc.pid:
                    rep.port = state["port"]
                    return
            if rep.proc.poll() is not None:
                tail = (rdir / "worker.log").read_text()[-2000:]
                raise FleetSpawnError(
                    f"replica {rep.rid} worker exited rc={rep.proc.returncode}; "
                    f"log tail:\n{tail}"
                )
            time.sleep(poll)
        rep.proc.kill()
        raise FleetSpawnError(
            f"replica {rep.rid} of {self.name!r} did not announce a port "
            f"within {self.spawn_timeout_s}s"
        )

    def _spawn_placed(self, rep: Replica, cfg: dict[str, Any]) -> None:
        """Place the replica on some registry host via its hostd agent
        (the client picks the least-placed healthy host and retries on
        survivors when one dies — ``placement.rpc`` faults land
        there). The worker is the same ``serving_host --fleet-worker``
        process; only who spawned it changes."""
        unit = self.placement.spawn(
            "replica", cfg, slot=f"{self.name}/{rep.rid}")
        rep.unit = unit
        rep.host = unit.address
        rep.port = unit.port

    def wait_ready(self, rid: str, timeout_s: float | None = None) -> Replica:
        """Block until the replica's ``/healthz`` answers 200, then mark
        it ``ready``. Raises :class:`FleetSpawnError` on timeout."""
        rep = self.get(rid)
        if rep is None:
            raise KeyError(f"replica {rid!r} not found")
        return self._wait_ready(rep, timeout_s)

    def _wait_ready(self, rep: Replica,
                    timeout_s: float | None = None) -> Replica:
        budget = timeout_s if timeout_s is not None else self.spawn_timeout_s
        deadline = time.monotonic() + budget
        poll = 0.02
        while time.monotonic() < deadline:
            if self._probe(rep)[0] == "ok":
                rep.state = "ready"
                flight.record("replica_state", model=self.name,
                              rid=rep.rid, state="ready")
                self._publish_states()
                return rep
            if rep.proc is not None and rep.proc.poll() is not None:
                break
            time.sleep(poll)
        # A failed replica may still have a LIVE worker (announced its
        # port but never answered ready): tear it down now — stop()'s
        # sweep skips "failed", so nothing else ever would.
        self._teardown(rep)
        rep.state = "failed"
        flight.record("replica_state", model=self.name,
                      rid=rep.rid, state="failed")
        self._forget(rep.rid)
        self._publish_states()
        raise FleetSpawnError(
            f"replica {rep.rid} of {self.name!r} never became ready "
            f"(port {rep.port})"
        )

    # -- health / drain / reap ------------------------------------------------

    def healthz(self, rid: str) -> str:
        """``ok`` | ``draining`` | ``unready`` | ``unreachable`` — the
        replica's own readiness answer (one probe, bounded)."""
        return self._healthz_body(rid)[0]

    def inflight(self, rid: str) -> int | None:
        """The replica's in-flight request count (None when it cannot
        be read — unreachable, or not draining and not in-process)."""
        rep = self.get(rid)
        if rep is None:
            return None
        if rep.server is not None:
            return rep.server.inflight
        return self._healthz_body(rid)[1].get("inflight")

    def _healthz_body(self, rid: str) -> tuple[str, dict[str, Any]]:
        return self._probe(self.get(rid))

    def _probe(self, rep: Replica | None) -> tuple[str, dict[str, Any]]:
        if rep is None or rep.port is None:
            return "unreachable", {}
        try:
            code, data, _ = self._probe_pool.request(
                "GET", f"http://{rep.host}:{rep.port}/healthz",
                timeout_s=2.0)
        except OSError:
            return "unreachable", {}
        try:
            body = json.loads(data)
        except Exception:  # graftlint: disable=swallowed-exception
            body = {}  # by contract: a probe never raises past here
        if code == 200:
            return "ok", body
        return body.get("status", "unready"), body

    def drain(self, rid: str) -> None:
        """Flip the replica into the draining state: it stops admitting
        (503 + ``Retry-After``) and its ``/healthz`` reports
        ``draining`` with the live in-flight count. A replica that died
        before (or while) being told is already as drained as it will
        ever get — tolerated, like :meth:`drained`'s ``unreachable``
        case, so a chaos kill racing a rollout's shift cannot crash the
        rollout. Same for a rid already reaped out of the book (an
        autoscaler scale-down racing a rollout that snapshotted it):
        a dead replica must never be flipped back into the live set."""
        rep = self.get(rid)
        if rep is None:
            log.warning("fleet %s: drain of unknown replica %s (already "
                        "reaped?); ignoring", self.name, rid)
            return
        if rep.state in ("stopped", "failed"):
            return  # already dead — as drained as it will ever get
        if rep.server is not None:
            rep.server.drain()
        elif rep.port is not None:
            # Placed replicas drain by the SAME direct POST (the drain
            # is the replica's own admission flip, not a host-lifecycle
            # action) — the hostd only owns spawn/reap/kill.
            try:
                self._probe_pool.request(
                    "POST", f"http://{rep.host}:{rep.port}/admin/drain",
                    b"{}", {"Content-Type": "application/json"},
                    timeout_s=2.0)
            except OSError:
                log.warning("fleet %s: replica %s unreachable for drain "
                            "(already dead?); treating as draining",
                            self.name, rid)
        rep.state = "draining"
        flight.record("replica_state", model=self.name,
                      rid=rep.rid, state="draining")
        self._publish_states()

    def drained(self, rid: str) -> bool:
        """Has a draining replica finished its in-flight work?"""
        rep = self.get(rid)
        if rep is None:
            return True
        if rep.server is not None:
            return rep.server.inflight == 0
        status, body = self._healthz_body(rid)
        if status == "unreachable":
            return True  # already gone
        return status == "draining" and body.get("inflight", 1) == 0

    def _teardown(self, rep: Replica, *, grace_s: float = 5.0) -> None:
        """Terminate a replica's worker (SIGTERM, SIGKILL after
        ``grace_s`` for process workers; server stop for in-process
        ones). Idempotent; does not touch the state machine."""
        if rep.server is not None:
            rep.server.stop()
            rep.server = None
        if rep.unit is not None:
            try:
                self.placement.reap(rep.unit)
            except Exception as e:  # noqa: BLE001 — a dead/partitioned host's
                # units are already gone; reap must stay idempotent
                log.warning("fleet %s: placed replica %s reap via %s failed "
                            "(host dead?): %s", self.name, rep.rid,
                            rep.unit.host.name, e)
            rep.unit = None
        if rep.proc is not None and rep.proc.poll() is None:
            rep.proc.terminate()
            try:
                rep.proc.wait(timeout=grace_s)
            except subprocess.TimeoutExpired:
                rep.proc.kill()
                rep.proc.wait(timeout=grace_s)

    def reap(self, rid: str, *, grace_s: float = 5.0) -> None:
        """Terminate a replica and mark it stopped. Idempotent."""
        rep = self.get(rid)
        if rep is None:
            return
        self._teardown(rep, grace_s=grace_s)
        rep.state = "stopped"
        flight.record("replica_state", model=self.name,
                      rid=rid, state="stopped", how="reap")
        self._forget(rid)
        self._publish_states()
        log.info("fleet %s: replica %s reaped", self.name, rid)

    def kill(self, rid: str) -> None:
        """Chaos verb: kill a replica WITHOUT drain (SIGKILL / abrupt
        server stop) — the failure the router must route around."""
        rep = self.get(rid)
        if rep is None:
            return
        if rep.proc is not None and rep.proc.poll() is None:
            os.kill(rep.proc.pid, signal.SIGKILL)
            rep.proc.wait(timeout=10)
        if rep.unit is not None:
            try:
                self.placement.kill(rep.unit)
            except Exception as e:  # noqa: BLE001 — chaos may have taken the
                # whole host with it; the unit is dead either way
                log.warning("fleet %s: placed replica %s kill via %s failed "
                            "(host dead?): %s", self.name, rep.rid,
                            rep.unit.host.name, e)
            rep.unit = None
        if rep.server is not None:
            rep.server.stop()
            rep.server = None
        rep.state = "stopped"
        flight.record("replica_state", model=self.name,
                      rid=rid, state="stopped", how="kill")
        self._forget(rid)
        self._publish_states()
        log.warning("fleet %s: replica %s KILLED (chaos)", self.name, rid)

    def reconcile(self) -> list[str]:
        """Placed-fleet liveness sweep: a replica whose HOST died takes
        no SIGCHLD here — nothing local notices. Probe each placed
        ready/starting replica; the unreachable ones are marked failed
        and forgotten, so the replica count drops and the autoscaler's
        next tick re-places them on the surviving hosts. Local fleets
        (no placement client) are a no-op. Returns the failed rids.

        Fencing: "unreachable" may mean dead — or PARTITIONED, still
        serving on the far side of a network cut. Before forgetting the
        unit its slot's generation is bumped, so every router forward
        from then on stamps a token the old worker cannot match: if the
        host heals, the zombie answers 410 instead of serving stale
        results under a retired identity. The unit itself is stashed so
        the sweep can reap it once the cut heals (see
        :meth:`_reap_superseded`)."""
        if self.placement is None:
            return []
        self._reap_superseded()
        failed: list[str] = []
        for rep in self.replicas():
            if rep.unit is None or rep.state not in ("starting", "ready"):
                continue
            if self._probe(rep)[0] != "unreachable":
                continue
            unit = rep.unit
            if getattr(unit, "slot", None):
                self.placement.bump_generation(unit.slot)
                with self._lock:
                    self._superseded.append(unit)
            rep.state = "failed"
            rep.unit = None  # fenced above; the zombie sweep owns the reap
            flight.record("replica_state", model=self.name,
                          rid=rep.rid, state="failed", how="reconcile")
            self._forget(rep.rid)
            failed.append(rep.rid)
            log.warning("fleet %s: placed replica %s on %s:%s unreachable — "
                        "marked failed for re-placement", self.name, rep.rid,
                        rep.host, rep.port)
        if failed:
            self._publish_states()
        return failed

    def _reap_superseded(self) -> None:
        """Reap zombies: units whose slot was re-placed while their host
        was unreachable. A reap that still cannot get through (cut not
        healed, or the hostd's breaker is open) keeps the unit queued
        for the next sweep; a reap that lands — or a host that was
        truly dead, where the hostd answers "already stopped" — drops
        it. Bounded: each sweep tries each zombie once."""
        with self._lock:
            pending = list(self._superseded)
        for unit in pending:
            try:
                self.placement.reap(unit)
            except Exception as e:  # noqa: BLE001 — partition still up or
                # breaker open; keep the zombie queued for the next sweep
                log.info("fleet %s: zombie %s on %s not reapable yet: %s",
                         self.name, unit.uid, unit.host.name, e)
                continue
            with self._lock:
                if unit in self._superseded:
                    self._superseded.remove(unit)
            flight.record("replica_state", model=self.name,
                          rid=unit.uid, state="stopped", how="zombie_reap")
            log.info("fleet %s: zombie %s on %s reaped after heal",
                     self.name, unit.uid, unit.host.name)

    def commit_version(self, version: int | None) -> None:
        """Persist a completed rollout's version into the serving
        definition, so every FUTURE spawn — an autoscaler heal, a
        restart — hosts the rolled-out version instead of silently
        resurrecting the old one. No-op for ``version=None`` (a roll
        onto the current definition changes nothing)."""
        if version is None:
            return
        from hops_tpu.modelrepo import registry

        with serving._registry_lock():
            reg = serving._load_registry()
            cfg = reg.get(self.name)
            if cfg is None:
                return
            meta = registry.get_model(
                cfg.get("model_name") or self.name, version)
            cfg["artifact_path"] = meta["path"]
            cfg["model_version"] = meta["version"]
            serving._save_registry(reg)

    def stop(self) -> None:
        """Reap every replica (fleet shutdown). Closes the manager:
        later ``spawn()`` calls — and spawns already in flight on other
        threads — fail with :class:`FleetSpawnError` and reap their own
        worker, so no replica process outlives the fleet."""
        with self._lock:
            self._closed = True
        for rep in self.replicas():
            self.reap(rep.rid)
        if self.placement is not None:
            self._reap_superseded()
        self._probe_pool.close()
