"""Front router: one endpoint over N serving replicas, least-loaded.

The thin request-routing tier the TF-paper systems framing calls for:
capacity (replica count) and versions (rollouts) change UNDER this
server without clients noticing. Design:

- **Least-loaded selection.** A scraper thread polls every replica's
  ``/metrics.json`` (its own port — the per-process registry) every
  ``scrape_interval_s`` and reads the serving gauges: queue depth
  (``hops_tpu_serving_batch_queue_depth``), in-flight executions
  (``hops_tpu_serving_inflight``) and the shed counter
  (``hops_tpu_serving_shed_total`` — its delta per scrape is the shed
  *rate*). The routing score adds the router's OWN per-replica
  in-flight count (exact and instant, where scrapes are stale by up to
  one interval — without it a burst between scrapes dogpiles the
  replica that looked idle last time). Lowest score wins; ties
  round-robin.
- **Routing around failure.** Each replica gets a
  ``resilience.CircuitBreaker``; a forward that fails at the transport
  (connect refused/reset/timeout) or with a replica-side 5xx records a
  failure and the request RETRIES on the next-best replica (predict is
  idempotent), so a dead or dying replica costs latency, not errors. A
  replica-side 503 (shedding, draining) retries elsewhere WITHOUT
  feeding the breaker — overload is load, not failure. 4xx is the
  client's problem and relays verbatim.
- **Per-tenant token buckets** (the layer above PR 5's per-replica
  load shedder): requests carry ``X-Tenant``; an empty bucket answers
  429 + ``Retry-After`` before any replica is touched.

Every forward passes through the ``router.forward`` fault point and an
explicit timeout (the ``blocking-call-no-deadline`` lint rule holds
this module to that).

**Zero-copy relay.** The forward path streams request and response
bodies through as raw bytes: the client's body goes onto the replica
wire unparsed, and the replica's response body returns to the client
byte-for-byte (2xx and 4xx/5xx alike) — no ``json.loads``/``json.dumps``
round-trip per hop (the ``relay-json-roundtrip`` lint rule keeps it
that way). Routing needs only the status code, headers and the
router's own scrape state; the body is parsed lazily in exactly two
places that need the object — the workload recorder's shape summaries
(armed captures only, after the reply is written) and the
``X-Hops-Debug: timeline`` merge (explicit operator ask). Tenant
extraction is header-based (``X-Tenant``). ``_reply`` recomputes only
the framing headers ``_relay_headers`` already owned.
"""

from __future__ import annotations

import json
import math
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

from hops_tpu.runtime import faultinject, flight
from hops_tpu.runtime.logging import get_logger
from hops_tpu.runtime.resilience import CircuitBreaker
from hops_tpu.telemetry import export as telemetry_export
from hops_tpu.telemetry import tracing
from hops_tpu.telemetry import workload
from hops_tpu.telemetry.metrics import REGISTRY
from hops_tpu.telemetry.spans import span

log = get_logger(__name__)

_m_requests = REGISTRY.counter(
    "hops_tpu_fleet_requests_total",
    "Requests received by the fleet router, per endpoint",
    labels=("model",),
)
_m_forwards = REGISTRY.counter(
    "hops_tpu_fleet_forwards_total",
    "Forwards per endpoint and replica (the balance to watch)",
    labels=("model", "replica"),
)
_m_retries = REGISTRY.counter(
    "hops_tpu_fleet_retries_total",
    "Forwards retried on another replica, per endpoint and reason "
    "(connect | error | shed)",
    labels=("model", "reason"),
)
_m_rate_limited = REGISTRY.counter(
    "hops_tpu_fleet_rate_limited_total",
    "Requests answered 429 by the per-tenant token bucket",
    labels=("tenant",),
)
_m_unrouted = REGISTRY.counter(
    "hops_tpu_fleet_unrouted_total",
    "Requests that exhausted every replica (503/5xx to the client)",
    labels=("model",),
)


#: Headers never relayed from a replica response: the body travels
#: through the router as VERBATIM bytes, but ``_reply`` still frames it
#: itself (one Content-Length it computed, one Content-Type it owns), so
#: passing the replica's framing through would send two (possibly
#: conflicting) Content-Lengths and truncate or hang clients. These
#: framing headers are the ONLY thing the relay recomputes.
_NO_RELAY_HEADERS = frozenset({
    "content-length", "content-type", "transfer-encoding", "connection",
    "keep-alive", "server", "date",
})


def _relay_headers(headers: Any) -> dict[str, str]:
    return {k: v for k, v in dict(headers).items()
            if k.lower() not in _NO_RELAY_HEADERS}


def _relayed_with_ctype(headers: Any) -> dict[str, str]:
    """Relay headers for a VERBATIM byte body: the non-framing headers
    plus the replica's own Content-Type — the bytes are the replica's
    serialization, so its declared type must travel with them
    (``_reply`` honors a caller-supplied Content-Type and recomputes
    only Content-Length)."""
    out = _relay_headers(headers)
    # Case-insensitive lookup: HTTP headers may arrive in any casing
    # (proxies/h2 commonly lowercase), and _relay_headers already
    # filtered every variant out.
    ctype = next(
        (v for k, v in dict(headers).items() if k.lower() == "content-type"),
        None,
    )
    if ctype:
        out["Content-Type"] = ctype
    return out


class TokenBucket:
    """Per-tenant rate limit: ``rate_rps`` tokens/s, ``burst`` deep.

    ``acquire()`` returns 0.0 when admitted (one token consumed) or the
    seconds until a token will exist — the 429's ``Retry-After``.
    Injectable clock for deterministic refill tests.
    """

    def __init__(self, rate_rps: float, burst: float,
                 clock=time.monotonic):
        if rate_rps <= 0 or burst <= 0:
            raise ValueError("rate_rps and burst must be > 0")
        self.rate_rps = float(rate_rps)
        self.burst = float(burst)
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = float(burst)  # guarded by: self._lock
        self._last = clock()  # guarded by: self._lock

    def acquire(self, n: float = 1.0) -> float:
        with self._lock:
            now = self._clock()
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rate_rps)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return 0.0
            return (n - self._tokens) / self.rate_rps

    @property
    def tokens(self) -> float:
        with self._lock:
            now = self._clock()
            return min(
                self.burst, self._tokens + (now - self._last) * self.rate_rps)

    @property
    def last_used(self) -> float:
        """Clock time of the last ``acquire`` — the LRU key the
        limiter's bucket-map eviction sorts on."""
        with self._lock:
            return self._last


class TenantRateLimiter:
    """``{tenant: {"rate_rps": r, "burst": b}}`` with an optional
    ``"default"`` entry covering unnamed tenants; no entry = unlimited.

    ``X-Tenant`` is untrusted client input, so the bucket map is
    HARD-bounded at ``max_buckets``: buckets that have refilled to
    full burst are pruned first (a full bucket admits exactly like a
    fresh one, so that eviction never changes an answer), and when a
    spray of unique tenants leaves nothing refilled, the
    least-recently-used bucket is evicted anyway. An evicted mid-limit
    tenant returns later at full burst — under attack, bounded memory
    beats exact answers; real tenants keep acquiring, stay recent, and
    survive the LRU pass.
    """

    def __init__(self, limits: dict[str, dict[str, float]] | None,
                 clock=time.monotonic, max_buckets: int = 4096):
        self._clock = clock
        self._limits = dict(limits or {})
        self.max_buckets = max_buckets
        self._lock = threading.Lock()
        self._buckets: dict[str, TokenBucket] = {}  # guarded by: self._lock

    def acquire(self, tenant: str) -> float:
        """0.0 = admitted, else seconds until this tenant has a token."""
        spec = self._limits.get(tenant, self._limits.get("default"))
        if spec is None:
            return 0.0
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                if len(self._buckets) >= self.max_buckets:
                    for name in [t for t, b in self._buckets.items()
                                 if b.tokens >= b.burst]:
                        del self._buckets[name]
                while len(self._buckets) >= self.max_buckets:
                    # Unique-tenant spray: nothing has refilled, but
                    # the cap is a hard bound — evict the coldest.
                    lru = min(self._buckets,
                              key=lambda t: self._buckets[t].last_used)
                    del self._buckets[lru]
                bucket = self._buckets[tenant] = TokenBucket(
                    spec["rate_rps"], spec.get("burst", spec["rate_rps"]),
                    clock=self._clock,
                )
        return bucket.acquire()

    def label_for(self, tenant: str) -> str:
        """Metric-safe tenant label: the tenant's own name only when
        it has an explicitly configured limit; everyone admitted under
        the ``"default"`` spec collapses to ``default`` — an untrusted
        ``X-Tenant`` spray must not mint unbounded counter children in
        the registry the router itself exports."""
        return tenant if tenant in self._limits else "default"


class _ReplicaView:
    """The router's read model of one replica: breaker, local inflight,
    last scraped load."""

    def __init__(self, rid: str, breaker_failures: int, breaker_reset_s: float):
        self.rid = rid
        self.breaker = CircuitBreaker(
            name=f"fleet-{rid}",
            failure_threshold=breaker_failures,
            reset_timeout_s=breaker_reset_s,
        )
        # += on an attribute is load/add/store bytecodes, NOT atomic:
        # two handler threads can lose an increment while both
        # decrements land, driving the count negative and permanently
        # skewing least-loaded selection toward this replica.
        self._count_lock = threading.Lock()
        self.inflight = 0  # guarded by: self._count_lock
        self.queue_depth = 0.0
        self.scraped_inflight = 0.0
        self.shed_rate = 0.0
        self._last_shed_total: float | None = None
        self.scrape_ok = True
        # Monotonic time of the last SUCCESSFUL scrape: `GET /fleet`
        # serves its age so a stale scrape (wedged or unreachable
        # replica) is distinguishable from a healthy idle one whose
        # numbers just happen to sit at zero.
        self.last_scrape_mono: float | None = None
        # Scraped hops_tpu_workload_capture_active: `GET /fleet`
        # reports which replica processes are capturing their streams.
        self.capture_active = 0.0

    def inflight_inc(self) -> None:
        with self._count_lock:
            self.inflight += 1

    def inflight_dec(self) -> None:
        with self._count_lock:
            self.inflight -= 1

    def score(self) -> float:
        with self._count_lock:
            inflight = self.inflight
        s = inflight + self.queue_depth + self.scraped_inflight \
            + self.shed_rate
        if not self.scrape_ok:
            s += 1.0  # deprioritize a replica we cannot see into
        return s


class Router:
    """The fleet's front HTTP server (``POST /predict``).

    ``manager`` needs only ``.name`` and ``.replicas()`` returning
    objects with ``rid`` / ``port`` / ``state`` — the real
    :class:`~hops_tpu.modelrepo.fleet.replicas.ReplicaManager` in
    production, a stub in router unit tests.
    """

    def __init__(
        self,
        manager: Any,
        *,
        rate_limits: dict[str, dict[str, float]] | None = None,
        scrape_interval_s: float = 0.25,
        forward_timeout_s: float = 30.0,
        max_attempts: int | None = None,
        breaker_failures: int = 3,
        breaker_reset_s: float = 5.0,
        port: int = 0,
        clock=time.monotonic,
    ):
        self.manager = manager
        self.name = manager.name
        self.scrape_interval_s = scrape_interval_s
        self.forward_timeout_s = forward_timeout_s
        self.max_attempts = max_attempts
        self.breaker_failures = breaker_failures
        self.breaker_reset_s = breaker_reset_s
        self.limiter = TenantRateLimiter(rate_limits, clock=clock)
        self._views_lock = threading.Lock()
        self._views: dict[str, _ReplicaView] = {}  # guarded by: self._views_lock
        self._rr = 0  # guarded by: self._views_lock
        self._lat_lock = threading.Lock()
        self._latencies: list[float] = []  # guarded by: self._lat_lock
        self._stop = threading.Event()
        name = self.name
        router = self

        m_requests = _m_requests.labels(model=name)
        m_unrouted = _m_unrouted.labels(model=name)

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args: Any) -> None:  # silence stderr spam
                pass

            def do_GET(self) -> None:
                try:
                    if telemetry_export.handle_metrics_path(self):
                        return
                    # Debug surfaces on the router's own port: ITS span
                    # ring (for in-process fleets this includes replica
                    # spans — one shared ring) and flight recorder.
                    if telemetry_export.handle_debug_path(self):
                        return
                    path = self.path.rstrip("/")
                    if path == "/healthz":
                        ready = router.routable()
                        if ready:
                            self._reply(200, {"status": "ok",
                                              "ready_replicas": len(ready)})
                        else:
                            self._reply(503, {"status": "unready",
                                              "ready_replicas": 0},
                                        headers={"Retry-After": "1"})
                        return
                    if path == "/fleet":
                        self._reply(200, router.describe())
                        return
                    self._reply(404, {"error": f"unknown path {self.path}"})
                except Exception as e:  # noqa: BLE001 — server must stay up
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})

            def do_POST(self) -> None:
                # Workload capture stamps the fleet-front-door ARRIVAL
                # — the recorded stream is what clients sent, with
                # rate-limited, unrouted, and handler-crash outcomes
                # included (their status IS the outcome). Defined
                # before any work so the outer except can record the
                # 500s it answers.
                t_arr_mono, t_arr_wall = time.monotonic(), time.time()
                body = b"{}"
                is_predict = False

                def capture(status: int, tspan: Any = None) -> None:
                    if not (is_predict and workload.capturing()):
                        return
                    try:
                        payload_obj = json.loads(body)
                    except ValueError:
                        payload_obj = None
                    workload.record_request(
                        surface="router",
                        endpoint=name,
                        path=self.path.rstrip("/"),
                        tenant=self.headers.get("X-Tenant"),
                        payload=payload_obj,
                        instances=(
                            payload_obj.get("instances")
                            if isinstance(payload_obj, dict) else None
                        ),
                        status=status,
                        latency_ms=(time.monotonic() - t_arr_mono) * 1e3,
                        trace_id=(
                            tspan.trace_id
                            if getattr(tspan, "sampled", False) else None
                        ),
                        t_mono=t_arr_mono,
                        t_wall=t_arr_wall,
                    )

                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(length) or b"{}"
                    path = self.path.rstrip("/")
                    if path.startswith("/admin/capture/"):
                        # Workload-capture control plane on the fleet's
                        # front door (status: GET /debug/workload).
                        try:
                            admin_payload = json.loads(body)
                        except ValueError:
                            admin_payload = {}
                        self._reply(
                            *workload.admin_action(path, admin_payload))
                        return
                    if path not in ("/predict", f"/v1/models/{name}:predict"):
                        self._reply(404, {"error": f"unknown path {self.path}"})
                        return
                    is_predict = True
                    m_requests.inc()
                    tenant = self.headers.get("X-Tenant", "default")
                    wait = router.limiter.acquire(tenant)
                    if wait > 0:
                        _m_rate_limited.inc(
                            tenant=router.limiter.label_for(tenant))
                        self._reply(
                            429,
                            {"error": f"tenant {tenant!r} rate limited"},
                            headers={"Retry-After": f"{math.ceil(wait)}"},
                        )
                        capture(429)
                        return
                    t0 = time.perf_counter()
                    # The trace starts (or, with an incoming
                    # `traceparent`, extends) at the fleet's front
                    # door; every forward hop below becomes a child,
                    # and the chosen sampling decision rides the
                    # injected header to the replicas.
                    debug = (self.headers.get(tracing.DEBUG_HEADER) or "")
                    relay_headers = (
                        {tracing.DEBUG_HEADER: debug} if debug else None)
                    # An explicit timeline ask force-samples: the
                    # operator debugging a request must get the
                    # breakdown whatever the ambient sample rate.
                    tspan = tracing.start_trace(
                        "fleet.request", headers=self.headers, model=name,
                        force_sample=debug.strip().lower() == "timeline")
                    with tspan:
                        with span("hops_tpu_fleet_request", model=name):
                            code, payload, headers = router.route(
                                body, extra_headers=relay_headers)
                        if debug.strip().lower() == "timeline":
                            # The ONE relay path that needs the object:
                            # the inline timeline merges the router's
                            # own spans into the replica's breakdown.
                            payload = router._merge_debug(payload, tspan)
                    # Rolling window behind recent_p99_ms(): the
                    # autoscaler's latency trigger reads this, the
                    # histogram above is for dashboards.
                    router.observe_latency(time.perf_counter() - t0)
                    if code >= 500:
                        m_unrouted.inc()
                    self._reply(code, payload, headers=headers)
                    # After the write — capture must not delay the
                    # response.
                    capture(code, tspan)
                except Exception as e:  # noqa: BLE001 — server must stay up
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                    # A handler crash is a client-visible 500: it
                    # belongs in the recorded error mix (capture()
                    # never raises past the recorder's drop counter).
                    capture(500)

            def _reply(self, code: int, body: dict[str, Any] | bytes,
                       headers: dict[str, str] | None = None) -> None:
                # Relay path hands bytes straight through (zero-copy:
                # the replica's serialized body is the response);
                # router-authored payloads (errors, /fleet) are dicts.
                # A relayed byte body keeps the REPLICA's declared
                # Content-Type (route() passes it through) — stamping
                # application/json on, say, an HTML error page from the
                # replica's HTTP stack would lie to the client; only
                # Content-Length is always recomputed.
                data = body if isinstance(body, bytes) else json.dumps(body).encode()
                hdrs = dict(headers or {})
                ctype = hdrs.pop("Content-Type", "application/json")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                for k, v in hdrs.items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

        self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"fleet-router-{name}",
        )
        self._thread.start()
        self._scraper = threading.Thread(
            target=self._scrape_loop, daemon=True,
            name=f"fleet-scraper-{name}",
        )
        self._scraper.start()
        log.info("fleet router for %s listening on 127.0.0.1:%d",
                 name, self.port)

    # -- views / telemetry scrape ---------------------------------------------

    def _view(self, rid: str) -> _ReplicaView:
        with self._views_lock:
            view = self._views.get(rid)
            if view is None:
                view = self._views[rid] = _ReplicaView(
                    rid, self.breaker_failures, self.breaker_reset_s)
            return view

    def _scrape_loop(self) -> None:
        interval = self.scrape_interval_s
        while not self._stop.wait(interval):
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 — the scraper must survive
                log.exception("fleet %s: scrape cycle failed", self.name)

    def scrape_once(self) -> None:
        """One pass over every routable replica's ``/metrics.json``.

        Also prunes views whose replica no longer exists (reaped,
        killed, or failed): every rollout and autoscale churn mints
        fresh rids, so without this the ``_views`` dict — a breaker and
        counters per rid ever seen — grows for the router's lifetime.
        """
        reps = self.manager.replicas()
        live = {rep.rid for rep in reps}
        with self._views_lock:
            for rid in [r for r in self._views if r not in live]:
                del self._views[rid]
        for rep in reps:
            if rep.state not in ("ready", "starting") or rep.port is None:
                continue
            view = self._view(rep.rid)
            snap = self._scrape_replica(rep.port)
            if snap is None:
                view.scrape_ok = False
                continue
            view.scrape_ok = True
            view.last_scrape_mono = time.monotonic()
            view.queue_depth = snap["queue_depth"]
            view.scraped_inflight = snap["inflight"]
            view.capture_active = snap["capture_active"]
            shed = snap["shed_total"]
            if view._last_shed_total is not None:
                view.shed_rate = max(0.0, shed - view._last_shed_total)
            view._last_shed_total = shed

    #: The only families the routing score reads — the scrape asks the
    #: replica for exactly these, so each poll renders and parses a
    #: four-family view instead of the replica's full registry snapshot
    #: (which grows with every instrumented subsystem).
    _SCRAPE_FAMILIES = (
        "hops_tpu_serving_batch_queue_depth",
        "hops_tpu_serving_inflight",
        "hops_tpu_serving_shed_total",
        "hops_tpu_workload_capture_active",
    )

    def _scrape_replica(self, port: int) -> dict[str, float] | None:
        timeout = max(0.5, self.scrape_interval_s * 2)
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics.json"
                f"?families={','.join(self._SCRAPE_FAMILIES)}",
                timeout=timeout,
            ) as resp:
                families = json.loads(resp.read()).get("metrics", {})
        except (OSError, ValueError):
            return None

        def gauge(family: str) -> float:
            rows = families.get(family, {}).get("samples", [])
            return float(sum(
                r["value"] for r in rows
                if r["labels"].get("model", self.name) == self.name
                and not r.get("suffix")
            ))

        def counter(family: str) -> float:
            rows = families.get(family, {}).get("samples", [])
            return float(sum(
                r["value"] for r in rows
                if r["labels"].get("model", self.name) == self.name
            ))

        return {
            "queue_depth": gauge("hops_tpu_serving_batch_queue_depth"),
            "inflight": gauge("hops_tpu_serving_inflight"),
            "shed_total": counter("hops_tpu_serving_shed_total"),
            "capture_active": gauge("hops_tpu_workload_capture_active"),
        }

    # -- selection / forwarding -----------------------------------------------

    def routable(self) -> list[Any]:
        """Replicas a request may go to right now: ready, with a port,
        breaker not open."""
        out = []
        for rep in self.manager.replicas():
            if rep.state != "ready" or rep.port is None:
                continue
            if self._view(rep.rid).breaker.state == "open":
                continue
            out.append(rep)
        return out

    def pick(self, exclude: set[str] = frozenset()) -> Any | None:
        """Least-loaded routable replica not in ``exclude``."""
        candidates = [r for r in self.routable() if r.rid not in exclude]
        if not candidates:
            return None
        with self._views_lock:
            self._rr += 1
            rr = self._rr
        scored = sorted(
            (self._view(r.rid).score(), (rr + i) % len(candidates), i)
            for i, r in enumerate(candidates)
        )
        return candidates[scored[0][2]]

    def route(
        self, body: bytes, extra_headers: dict[str, str] | None = None
    ) -> tuple[int, dict[str, Any] | bytes, dict[str, str]]:
        """Forward ``body`` to the best replica, retrying the next-best
        on transport failure / replica 5xx / shed-503 until attempts or
        replicas run out. Returns ``(status, payload, headers)`` where
        ``payload`` is the replica's response body as VERBATIM bytes —
        the zero-copy relay contract: the forward path never parses or
        re-serializes either body (routing needs only the status code
        and headers), so 2xx and 4xx/5xx alike reach the client
        byte-for-byte as the replica sent them. Only the router's own
        no-replica 503 is a dict (it authored it).

        Tracing: each forward attempt is a ``fleet.forward`` child span
        of the caller's active trace, tagged with the replica id, the
        attempt index, and the replica breaker's state at selection
        time — so retries read as SIBLING hops under one request, and
        the ``traceparent`` injected on the wire makes the replica's
        own ``serving.request`` span a child of the hop that reached
        it."""
        attempts = self.max_attempts or max(3, len(self.manager.replicas()) + 1)
        tried: set[str] = set()
        last: tuple[int, dict[str, Any], dict[str, str]] | None = None
        for attempt in range(attempts):
            rep = self.pick(exclude=tried)
            if rep is None:
                break
            tried.add(rep.rid)
            view = self._view(rep.rid)
            if not view.breaker.allow():
                continue  # raced open, or half-open probe budget spent
            _m_forwards.inc(model=self.name, replica=rep.rid)
            view.inflight_inc()
            fspan = tracing.child_span(
                "fleet.forward", replica=rep.rid, attempt=attempt,
                breaker=view.breaker.state,
            )
            try:
                with fspan:
                    try:
                        # Chaos point. ANY armed error class models a
                        # transport failure on this hop (the catalog
                        # promises a retry, and the fault grammar defaults
                        # to RuntimeError) — only the real forward below
                        # narrows to transport exception types.
                        faultinject.fire("router.forward")
                    except Exception as e:
                        raise urllib.error.URLError(e) from e
                    code, payload, headers = self._forward(
                        rep.port, body, extra_headers)
                    fspan.annotate(status=code)
            except (OSError, urllib.error.URLError) as e:
                # Transport failure: the replica is gone or wedged —
                # breaker strike, retry elsewhere. The request has NOT
                # been answered, so this retry is invisible to the
                # client beyond latency.
                view.breaker.record_failure()
                _m_retries.inc(model=self.name, reason="connect")
                flight.record("retry", op="router.forward",
                              reason="connect", replica=rep.rid,
                              model=self.name,
                              error=type(getattr(e, "reason", e)).__name__)
                continue
            finally:
                view.inflight_dec()
            if code < 400:
                view.breaker.record_success()
                # Non-framing replica headers relay on success too —
                # the same contract the 4xx path already kept.
                return code, payload, headers
            if code in (429, 503):
                # Shedding/draining: load, not failure. Don't strike
                # the breaker; try a less-loaded replica.
                _m_retries.inc(model=self.name, reason="shed")
                flight.record("retry", op="router.forward", reason="shed",
                              replica=rep.rid, model=self.name)
                last = (code, payload, headers)
                continue
            if code >= 500:
                view.breaker.record_failure()
                _m_retries.inc(model=self.name, reason="error")
                flight.record("retry", op="router.forward", reason="error",
                              replica=rep.rid, model=self.name, status=code)
                last = (code, payload, headers)
                continue
            # 4xx: the client's request is bad everywhere — relay as-is.
            return code, payload, headers
        if last is not None:
            return last
        return (
            503,
            {"error": f"no routable replicas for {self.name!r}"},
            {"Retry-After": "1"},
        )

    def _forward(
        self, port: int, body: bytes,
        extra_headers: dict[str, str] | None = None,
    ) -> tuple[int, bytes, dict[str, str]]:
        headers = {"Content-Type": "application/json", **(extra_headers or {})}
        # Propagate the trace across the process boundary: the active
        # span here is this hop's fleet.forward, so the replica's
        # serving.request parents to exactly the hop that reached it.
        tracing.inject_headers(headers)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/models/{self.name}:predict",
            data=body, headers=headers,
        )
        try:
            with urllib.request.urlopen(
                req, timeout=self.forward_timeout_s
            ) as resp:
                # Zero-copy: the replica's body relays as raw bytes —
                # no json.loads/json.dumps round-trip on the hot path.
                return resp.status, resp.read(), _relayed_with_ctype(resp.headers)
        except urllib.error.HTTPError as e:
            body = e.read()
            if body:
                return e.code, body, _relayed_with_ctype(e.headers)
            return (
                e.code,
                json.dumps({"error": f"replica answered {e.code}"}).encode(),
                _relay_headers(e.headers),
            )

    def _merge_debug(
        self, payload: dict[str, Any] | bytes, tspan: Any
    ) -> dict[str, Any] | bytes:
        """Fold the router's own spans for this trace into the inline
        timeline a replica returned under ``X-Hops-Debug: timeline``
        (dedup by span id: with in-process replicas the shared ring
        already holds the replica's spans). The one relay path that
        parses the relayed bytes — the operator asked for the merged
        object. A non-JSON body relays untouched."""
        if isinstance(payload, bytes):
            raw = payload
            try:
                parsed = json.loads(payload)
            except ValueError:
                return raw
            if not isinstance(parsed, dict):
                # Valid JSON but not an object (list/scalar): nothing
                # to merge into — relay the ORIGINAL bytes, not a
                # re-serialization of the parse.
                return raw
            payload = parsed
        if not isinstance(payload, dict):
            return payload
        dbg = payload.setdefault("debug", {})
        rows = {r["span_id"]: r for r in dbg.get("timeline", [])
                if isinstance(r, dict) and "span_id" in r}
        for r in tracing.timeline(tspan):
            rows.setdefault(r["span_id"], r)
        merged = sorted(rows.values(), key=lambda r: r.get("start", 0.0))
        if merged:
            dbg["timeline"] = merged
            dbg.setdefault("trace_id", merged[0].get("trace_id"))
        return payload

    # -- surface --------------------------------------------------------------

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def breaker_state(self, rid: str) -> str:
        return self._view(rid).breaker.state

    def observe_latency(self, seconds: float) -> None:
        with self._lat_lock:
            self._latencies.append(seconds)
            if len(self._latencies) > 2048:
                del self._latencies[:1024]

    def recent_p99_ms(self) -> float | None:
        """p99 of the most recent window of router-observed latencies
        (the autoscaler's optional latency trigger)."""
        with self._lat_lock:
            window = list(self._latencies[-512:])
        if not window:
            return None
        window.sort()
        return window[min(len(window) - 1, int(len(window) * 0.99))] * 1e3

    def fleet_load(self) -> float | None:
        """Mean routing score per routable replica — the autoscaler's
        primary signal (None when nothing is routable)."""
        routable = self.routable()
        if not routable:
            return None
        return sum(self._view(r.rid).score() for r in routable) / len(routable)

    def describe(self) -> dict[str, Any]:
        reps = []
        now = time.monotonic()
        for rep in self.manager.replicas():
            view = self._view(rep.rid)
            reps.append({
                "rid": rep.rid,
                "state": rep.state,
                "port": rep.port,
                "version": getattr(rep, "version", None),
                "score": round(view.score(), 3),
                "breaker": view.breaker.state,
                # How long the breaker has sat in that state, and how
                # stale the scraped load numbers are (None = never
                # scraped): without the ages a wedged replica whose
                # last scrape said "idle" is indistinguishable from a
                # healthy idle one.
                "breaker_state_age_s": round(view.breaker.state_age_s(), 3),
                "last_scrape_age_s": (
                    round(now - view.last_scrape_mono, 3)
                    if view.last_scrape_mono is not None else None
                ),
                # Scraped per-replica workload-capture status (for
                # in-process fleets every replica shares the router's
                # process-global recorder, so these agree).
                "capture": bool(view.capture_active),
            })
        return {"model": self.name, "replicas": reps,
                "ready": sum(1 for r in reps if r["state"] == "ready"),
                "capture": workload.status()}

    def stop(self) -> None:
        self._stop.set()
        self._server.shutdown()
        self._server.server_close()
        self._scraper.join(timeout=5)
